#!/usr/bin/env python3
"""Benchmark: ClusterPolicy install -> Ready, end to end.

The reference's only published performance surface is operand-readiness
time, CI-bounded at 15 minutes for 6 DaemonSets on a real GPU node
(tests/e2e/gpu_operator_test.go:137, see BASELINE.md). This bench drives
the identical flow — create ClusterPolicy, operator renders + applies all
operand states, every DaemonSet schedules and reports available on a
4-host v5e-16 node pool, CR status flips Ready — against the in-memory
apiserver + cluster sim (the "CPU-only kind cluster" configuration,
BASELINE config 1/4 shape), so the number isolates operator overhead:
reconcile latency, render cost, state-machine passes, watch fan-out.

Prints ONE compact JSON line: {"metric", "value", "unit", "vs_baseline",
...headline numbers}. vs_baseline is the reference bound (900 s) over our
measured time. The full structure (per-run timings, scale/stat blocks,
on-chip validation payloads — smoke matmul, pallas triad HBM bandwidth,
flash attention, psum allreduce) is written to BENCH_DETAIL.json; pass
--full to print it instead. The compact line exists because the driver
records only a ~2,000-char tail of stdout (BENCH_r04 truncated mid-object).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# ensure the 8-device virtual CPU mesh is available for the multichip
# details block (must happen before any backend initialization; same
# recipe as tests/conftest.py)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

REFERENCE_READY_BOUND_S = 900.0  # tests/e2e/gpu_operator_test.go:137
SIM_CONTAINER_START_S = 0.25  # simulated image-pull/container-start latency


def bench_install_to_ready(
    nodes: int = 4,
    transport: str = "inproc",
    cached_reads: bool = True,
    collect_stats: bool = False,
    deadline_s: float = 120.0,
    settle_s: float = 0.0,
    perturb_flips: int = 8,
    chaos=None,
    sim_pods: bool = True,
):
    """transport="inproc": operator calls the fake apiserver as dict ops.
    transport="http": the same fake apiserver is served over real TCP
    (kube/httpserver.py) and the operator runs on HttpClient — the number
    then includes JSON serialization, watch-stream delivery, and
    per-request connection setup. The cluster sim (standing in for
    kubelets + the DaemonSet controller) stays in-process either way.

    ``cached_reads=False`` bypasses the informer-cache read path (the
    round-3 behavior) so the apiserver-traffic saving is measurable.
    ``collect_stats=True`` returns ``(elapsed, stats)`` with wire-request
    counts per verb and two requests-per-reconcile rates:

    - ``requests_per_reconcile`` (headline): measured over the POST-Ready
      window — ``settle_s`` of quiet steady state plus ``perturb_flips``
      admin label flips the operator must repair (one deploy-gate label
      removed per flip, written straight into the store the way kubectl
      would). This is the steady-state control-plane cost per unit of
      actual change, the number that must stay flat as the cluster grows
      (O(changes), not O(nodes)).
    - ``install.requests_per_reconcile``: the old whole-run rate. Install
      necessarily writes every node once (the initial label stamp), so
      this one scales with node count by construction and is kept only
      for continuity with earlier BENCH rounds.

    The steady block also reports the WRITE side on its own
    (``steady.write_requests`` / ``steady.writes_per_flip``): the flat-
    write-rate property — each admin flip costs a constant number of
    repair writes no matter how many nodes exist — is the O(changes)
    claim in its purest form, independent of how many cached reads a
    reconcile performs.

    ``sim_pods=False`` runs the cluster sim without materializing one
    Pod per (DaemonSet, node) — at 16,384 nodes that is ~147k pod
    objects standing in for kubelet bookkeeping the control-plane gate
    does not measure; DaemonSet availability (what install-to-Ready
    waits on) is simulated either way."""
    from tpu_operator.api.clusterpolicy import (
        CLUSTER_POLICY_API_VERSION,
        CLUSTER_POLICY_KIND,
        new_cluster_policy,
    )
    from tpu_operator.controllers.clusterpolicy_controller import (
        ClusterPolicyReconciler,
        setup_with_manager,
    )
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.manager import Manager
    from tpu_operator.kube.sim import ClusterSim, make_tpu_node

    ns = "tpu-operator"
    store = FakeClient()
    for i in range(nodes):  # v5e-16: 4 hosts x 4 chips
        store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "4x4"))
    apiserver = None
    if transport == "http":
        from tpu_operator.kube.http_client import HttpClient
        from tpu_operator.kube.httpserver import FakeApiServer

        # chaos: a seeded ChaosDirector (kube/chaos.py) injected at the
        # HTTP layer — chaos_converge_s measures install→Ready through
        # the standard fault schedule with the real retry/breaker path
        apiserver = FakeApiServer(store, chaos=chaos).start()
        client = HttpClient(apiserver.base_url, watch_stall_seconds=10.0)
    else:
        client = store
    sim = ClusterSim(
        store, ready_delay=SIM_CONTAINER_START_S, tick=0.01, create_pods=sim_pods
    ).start()
    mgr = Manager(client, namespace=ns)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, ns), cached_reads=cached_reads)
    import prometheus_client

    from tpu_operator.controllers.operator_metrics import get_metrics

    get_metrics()  # ensure the counter exists before sampling it

    def reconcile_count() -> float:
        # public sample API (the _value attribute is private and has moved
        # across prometheus_client versions)
        return (
            prometheus_client.REGISTRY.get_sample_value(
                "tpu_operator_reconciliation_total"
            )
            or 0.0
        )

    reconciles_before = reconcile_count()
    mgr.start()
    try:
        t0 = time.perf_counter()
        # admin-side, like kubectl (and like the soak/RBAC-gate tests):
        # the CR install is not the operator's own traffic — and under a
        # chaos schedule a store-create can't eat an injected fault on a
        # POST the client (correctly) never retries
        store.create(new_cluster_policy())
        deadline = t0 + deadline_s
        elapsed = None
        while time.perf_counter() < deadline:
            cp = store.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            if cp.get("status", {}).get("state") == "ready":
                dses = store.list("apps/v1", "DaemonSet", ns)
                # election-gated autotuner + compile-cache: desired/available 0 here
                if len(dses) == 11 and all(
                    ds.get("status", {}).get("numberAvailable")
                    == (0 if ds["metadata"]["name"] in ("tpu-autotuner", "tpu-compile-cache") else nodes)
                    for ds in dses
                ):
                    elapsed = time.perf_counter() - t0
                    break
            time.sleep(0.005)
        if elapsed is None:
            raise RuntimeError("ClusterPolicy never became Ready")
        if not collect_stats:
            return elapsed

        def requests_total() -> int:
            return sum((getattr(client, "request_counts", {}) or {}).values())

        def writes_total() -> int:
            counts = getattr(client, "request_counts", {}) or {}
            return sum(counts.get(v, 0) for v in ("PUT", "PATCH", "POST", "DELETE"))

        ready_reconciles = reconcile_count()
        ready_requests = requests_total()
        ready_writes = writes_total()
        steady_t0 = time.monotonic()
        if settle_s:
            time.sleep(settle_s)
        # controlled perturbation: an admin (store-direct, uncounted) strips
        # one deploy-gate label; the operator must notice and restore it.
        # Each flip is exactly one unit of real change, so the post-Ready
        # requests/reconciles ratio measures the marginal cost of a change.
        from tpu_operator import consts as _consts

        gate = _consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd"
        for i in range(perturb_flips):
            node_name = f"tpu-{i % nodes}"
            store.patch("v1", "Node", node_name, {"metadata": {"labels": {gate: None}}})
            flip_deadline = time.monotonic() + 15.0
            while time.monotonic() < flip_deadline:
                labels = store.get("v1", "Node", node_name)["metadata"].get("labels") or {}
                if labels.get(gate) == "true":
                    break
                time.sleep(0.002)
            else:
                raise RuntimeError(f"operator never restored {gate} on {node_name}")
        time.sleep(0.2)  # let the last repair's echo/status bookkeeping land

        reconciles = reconcile_count() - reconciles_before
        counts = dict(getattr(client, "request_counts", {}) or {})
        total = sum(counts.values())
        steady_reconciles = int(reconcile_count() - ready_reconciles)
        steady_requests = requests_total() - ready_requests
        steady_writes = writes_total() - ready_writes
        steady_window = max(time.monotonic() - steady_t0, 1e-9)
        stats = {
            "cached_reads": cached_reads,
            "reconciles": int(reconciles),
            "wire_requests": counts,
            "wire_requests_total": total,
            "install": {
                "reconciles": int(ready_reconciles - reconciles_before),
                "wire_requests_total": ready_requests,
                "requests_per_reconcile": (
                    round(ready_requests / (ready_reconciles - reconciles_before), 1)
                    if ready_reconciles > reconciles_before
                    else None
                ),
            },
            "steady": {
                "label_flips": perturb_flips,
                "reconciles": steady_reconciles,
                "wire_requests_total": steady_requests,
                # the write side alone: flat writes-per-flip across scales
                # IS the O(changes) property under perturbation
                "write_requests": steady_writes,
                "writes_per_flip": (
                    round(steady_writes / perturb_flips, 2) if perturb_flips else 0.0
                ),
                "window_s": round(steady_window, 3),
                "write_rate_per_s": round(steady_writes / steady_window, 2),
            },
            "requests_per_reconcile": (
                round(steady_requests / steady_reconciles, 1) if steady_reconciles else 0.0
            ),
        }
        return elapsed, stats
    finally:
        mgr.stop()
        sim.stop()
        if apiserver is not None:
            apiserver.stop()


class TraceAttribution:
    """Flight-recorder listener decomposing every completed reconcile
    trace into queue wait, per-verb apiserver time/requests, and body
    compute — the numbers that explain a requests-per-reconcile curve.
    Registered via ``FlightRecorder.add_listener`` so the bounded ring
    never loses data to eviction."""

    def __init__(self):
        self.controllers: dict = {}
        self.traces = 0
        self.incomplete = 0
        self.retried_api_calls = 0
        self.min_accounted = 1.0

    def __call__(self, t) -> None:
        root = t.root
        ctl = root.attrs.get("controller", "?")
        c = self.controllers.setdefault(ctl, {
            "reconciles": 0, "wall_s": 0.0, "queue_wait_s": 0.0,
            "api_s": 0.0, "api_requests": 0, "by_verb": {},
            "by_shard": {}, "min_accounted": 1.0,
        })
        c["reconciles"] += 1
        c["wall_s"] += root.duration
        c["queue_wait_s"] += float(root.attrs.get("queue_wait_s") or 0.0)
        # per-shard owners: which pool-shard's reconciles carry the wall
        # time / queue wait (the sharded run's attribution surface)
        shard = str(root.attrs.get("shard") or "")
        s = c["by_shard"].setdefault(shard, {
            "reconciles": 0, "wall_s": 0.0, "queue_wait_s": 0.0,
        })
        s["reconciles"] += 1
        s["wall_s"] += root.duration
        s["queue_wait_s"] += float(root.attrs.get("queue_wait_s") or 0.0)
        for s in t.spans[1:]:
            if s.name != "api" or s.end is None:
                continue
            # no attempts attr = ZERO wire sends (a breaker fast-fail):
            # counting it as 1 would inflate requests_per_reconcile in
            # exactly the degraded runs attribution exists to explain
            attempts = int(s.attrs.get("attempts") or 0)
            if attempts > 1:
                self.retried_api_calls += 1
            verb = s.attrs.get("verb", "?")
            v = c["by_verb"].setdefault(verb, {"requests": 0, "s": 0.0})
            v["requests"] += attempts
            v["s"] += s.duration
            c["api_s"] += s.duration
            c["api_requests"] += attempts
        # spans past the per-trace cap arrive pre-aggregated (a 4096-node
        # label sweep is one reconcile with 4096+ api spans); "attempt"
        # entries are skipped — their time already rides the api entry
        for (name, verb, _kind), (_count, requests, seconds) in t.overflow.items():
            if name != "api":
                continue
            v = c["by_verb"].setdefault(verb, {"requests": 0, "s": 0.0})
            v["requests"] += requests
            v["s"] += seconds
            c["api_s"] += seconds
            c["api_requests"] += requests
        self.traces += 1
        if not t.complete():
            self.incomplete += 1
        accounted = t.accounted_fraction()
        c["min_accounted"] = min(c["min_accounted"], accounted)
        self.min_accounted = min(self.min_accounted, accounted)

    def block(self) -> dict:
        """Per-controller breakdown: wall time split queue-wait / api (by
        verb) / body-other, request counts per reconcile by verb."""
        out = {}
        for ctl, c in sorted(self.controllers.items()):
            n = max(c["reconciles"], 1)
            wall, api_s = c["wall_s"], c["api_s"]
            body = max(0.0, wall - api_s)
            out[ctl] = {
                "reconciles": c["reconciles"],
                "wall_s": round(wall, 3),
                "queue_wait_s": round(c["queue_wait_s"], 3),
                "api_s": round(api_s, 3),
                "body_other_s": round(body, 3),
                "requests_per_reconcile": round(c["api_requests"] / n, 2),
                # worst per-trace accounting consistency (Trace.
                # accounted_fraction's unclipped-vs-clipped check), NOT
                # re-derived from the aggregates above — that algebra is
                # identically 100% and would hide broken traces
                "accounted_pct": round(100 * c["min_accounted"], 1),
                # slowest shards first: the named owners of this
                # controller's wall time (shard "" = the unsharded/global
                # queue)
                "by_shard": {
                    shard: {
                        "reconciles": s["reconciles"],
                        "wall_s": round(s["wall_s"], 3),
                        "queue_wait_s": round(s["queue_wait_s"], 3),
                    }
                    for shard, s in sorted(
                        c["by_shard"].items(),
                        key=lambda kv: -kv[1]["wall_s"],
                    )[:8]
                },
                "by_verb": {
                    verb: {
                        "requests": v["requests"],
                        "s": round(v["s"], 3),
                        "rpr": round(v["requests"] / n, 2),
                    }
                    for verb, v in sorted(c["by_verb"].items())
                },
            }
        return out


def tpu_details() -> dict:
    """On-chip validation payloads when an accelerator is visible."""
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception as e:  # noqa: BLE001
        return {"platform": f"unavailable: {e}"}
    details = {"platform": platform, "devices": len(jax.devices())}
    if os.environ.get("BENCH_SKIP_DEVICE", ""):
        return details
    try:
        from tpu_operator.workloads.smoke import run_smoke

        t0 = time.perf_counter()
        run_smoke(size=512)
        details["smoke_s"] = round(time.perf_counter() - t0, 3)
        from tpu_operator.workloads.kernels import hbm_bandwidth_probe

        probe = hbm_bandwidth_probe(
            size_mb=128 if platform != "cpu" else 16, iters=50 if platform != "cpu" else 2
        )
        if probe.get("unstable_timing"):
            # slope collapsed under relay noise: only an overhead-inclusive
            # lower bound is available — never present it as the measurement
            details["triad_gbps_lower_bound"] = round(probe["bandwidth_gbps"], 2)
        else:
            details["triad_gbps"] = round(probe["bandwidth_gbps"], 2)
        detail = {
            k: round(probe[k], 1) if isinstance(probe.get(k), float) else probe.get(k)
            for k in (
                "inclusive_gbps",
                "dispatch_overhead_ms_est",
                "iters",
                "min_times_ms",
                "unstable_timing",
            )
            if k in probe
        }
        if detail:
            details["triad_detail"] = detail
        from tpu_operator.workloads.matmul_bench import PEAK_TFLOPS, matmul_tflops

        mm = matmul_tflops(size=8192 if platform != "cpu" else 512, iters=16 if platform != "cpu" else 2)
        key = "matmul_bf16_tflops_lower_bound" if mm.get("unstable_timing") else "matmul_bf16_tflops"
        details[key] = round(mm["tflops"], 2)
        from tpu_operator.workloads.matmul_bench import chip_generation

        gen = chip_generation()
        if gen in PEAK_TFLOPS and not mm.get("unstable_timing"):
            details["mxu_utilization_pct"] = round(100 * mm["tflops"] / PEAK_TFLOPS[gen], 1)
        if platform != "cpu":
            # quantized-inference rate: int8 x int8 -> int32 on the MXU's
            # double-rate path (v5e: 394 TOP/s peak)
            from tpu_operator.workloads.matmul_bench import PEAK_INT8_TOPS, int8_matmul_tops

            i8 = int8_matmul_tops(size=8192, iters=16)
            key = "matmul_int8_tops_lower_bound" if i8.get("unstable_timing") else "matmul_int8_tops"
            details[key] = round(i8["tops"], 2)
            if gen in PEAK_INT8_TOPS and not i8.get("unstable_timing"):
                details["int8_mxu_utilization_pct"] = round(
                    100 * i8["tops"] / PEAK_INT8_TOPS[gen], 1
                )

            # long-context hot op: pallas flash attention vs XLA dense
            from tpu_operator.workloads.flashattention import flash_attention_bench

            # 6 timing pairs (default 4): the relay chip is multi-tenant
            # and its throughput varies by period — more pairs tighten
            # the honest median without cherry-picking minima
            fa = flash_attention_bench(seq_len=8192, heads=8, reps=6)
            details["flash_attention_8k"] = {
                "time_ms": round(fa["flash_time_ms"], 2),
                "tflops": round(fa["flash_tflops"], 1),
                "speedup_vs_dense": round(fa.get("speedup_vs_dense", 0.0), 2),
                "fwd_bwd_ms": round(fa["flash_fwd_bwd_ms"], 2),
                # two training baselines, naive and remat'd dense, timed
                # by the same all-cotangents chain as the flash path (a
                # dq-only chain once let DCE delete work asymmetrically
                # and inflate this ratio to ~90x; honest value ~6-6.5x)
                "train_step_speedup_vs_dense": round(
                    fa.get("train_step_speedup_vs_dense", 0.0), 2
                ),
                "train_step_speedup_vs_remat_dense": round(
                    fa.get("train_step_speedup_vs_remat_dense", 0.0), 2
                ),
            }
            # long-context scaling: the kernel's achieved rate RISES with
            # sequence length (diagonal over-compute amortizes; the
            # triangle walk has no bubbles to grow)
            scaling = {}
            for s_len in (16384, 32768):
                fs = flash_attention_bench(seq_len=s_len, heads=8, iters=4, reps=3)
                scaling[f"{s_len // 1024}k"] = {
                    "time_ms": round(fs["flash_time_ms"], 2),
                    "tflops": round(fs["flash_tflops"], 1),
                    "fwd_bwd_ms": round(fs["flash_fwd_bwd_ms"], 2),
                }
            details["flash_attention_scaling"] = scaling

            from tpu_operator.workloads.allreduce import run_allreduce

            ar = run_allreduce(sizes_mb=(16,), iters=10)
            if ar["devices"] > 1:
                details["allreduce_busbw_gbps_per_chip"] = round(
                    ar["peak_busbw_gbps_per_chip"], 2
                )
            else:
                # a single-chip psum proves the collective lowers and runs,
                # but measures dispatch latency, not an interconnect — never
                # report it beside real bandwidth numbers
                details["allreduce"] = {k: ar[k] for k in ("devices", "correctness_only")}
        # the metrics exporter's own active probes (the DCGM-analog
        # series), collected from this chip — proves the exported
        # utilization gauges populate on real hardware
        from tpu_operator.agents.metrics_exporter_agent import MetricsExporterAgent

        exporter = MetricsExporterAgent(node_name="bench")
        exporter.collect_device_stats()
        exporter.probe_utilization()
        series = {
            "chips": int(exporter.chips.labels("bench")._value.get()),
            "matmul_tflops": round(exporter.matmul_tflops.labels("bench")._value.get(), 2),
        }
        util = exporter.mxu_utilization.labels("bench")._value.get()
        if util:
            series["mxu_utilization_pct"] = round(util, 1)
        details["exporter_series"] = series
        # on CPU-only hosts the virtual mesh below owns the (fake-device)
        # collective measurement
        details["multichip_virtual_mesh"] = _virtual_mesh_details()
    except Exception as e:  # noqa: BLE001 — details are best-effort
        details["device_error"] = str(e)
    return details


def _virtual_mesh_details() -> dict:
    """The multi-chip sharding path exercised on the 8-device virtual CPU
    mesh (xla_force_host_platform_device_count): psum allreduce + ring
    attention exactness. Bandwidth here is host-memory movement on fake
    devices — reported to show the path runs, never as an ICI number."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    cpu = jax.devices("cpu")
    if len(cpu) < 8:
        return {"skipped": f"only {len(cpu)} cpu devices"}
    from tpu_operator.workloads.allreduce import run_allreduce
    from tpu_operator.workloads.ringattention import run_ring_attention_check

    ar = run_allreduce(sizes_mb=(4,), devices=cpu[:8], iters=5)
    ring = run_ring_attention_check(mesh=Mesh(np.array(cpu[:8]), ("sp",)))
    return {
        "note": "8 virtual CPU devices; validates sharding/collectives, not ICI",
        "devices": 8,
        "psum_busbw_gbps_per_chip": round(ar["peak_busbw_gbps_per_chip"], 2),
        "ring_attention_max_abs_err": float(ring["max_abs_err"]),
    }


def _multiprocess_distributed_details() -> dict:
    """Live multi-process jax.distributed over localhost TCP (gang
    contract end to end; closest this 1-chip environment gets to
    BASELINE 4/5): a 2-host single-slice gang, and a 2-slice world over
    the DCN coordinator (MEGASCALE_* env path)."""
    try:
        from tpu_operator.workloads.multiproc import (
            run_multiprocess_check,
            run_multislice_check,
        )

        report = run_multiprocess_check(num_workers=2, devices_per_worker=4)
        multislice = run_multislice_check(num_slices=2, hosts_per_slice=1, devices_per_worker=4)
        four_slice = run_multislice_check(num_slices=4, hosts_per_slice=2, devices_per_worker=1)
        return {
            "note": "2 local processes x 4 virtual CPU devices, real jax.distributed/TCP",
            "global_devices": report["global_devices"],
            "psum_ok": report["psum_ok"],
            "psum_chain_ms": round(report["psum_chain_ms"], 2),
            "ring_attention_max_err": report["ring_attention_max_err"],
            "two_slice_dcn": {
                "slices": multislice["num_slices"],
                "global_devices": multislice["global_devices"],
                "psum_ok": multislice["psum_ok"],
            },
            # 8 processes in 4 slice blocks: the process-id derivation at a
            # non-trivial (slice, host) layout
            "four_slice_dcn": {
                "slices": four_slice["num_slices"],
                "processes": four_slice["num_workers"],
                "global_devices": four_slice["global_devices"],
                "psum_ok": four_slice["psum_ok"],
            },
        }
    except Exception as e:  # noqa: BLE001 — details are best-effort
        return {"error": str(e)[-500:]}


def _compact_attribution(attribution: dict) -> dict:
    for scale in ("16384", "4096", "1024", "256", "64"):
        block = attribution.get(scale)
        if not block:
            continue
        ctl = (block.get("controllers") or {}).get("clusterpolicy")
        if not ctl:
            continue
        wall = max(ctl["wall_s"] + ctl["queue_wait_s"], 1e-9)
        return {
            "nodes": int(scale),
            "reconciles": ctl["reconciles"],
            "queue_wait_pct": round(100 * ctl["queue_wait_s"] / wall, 1),
            "api_pct": round(100 * ctl["api_s"] / wall, 1),
            "body_pct": round(100 * ctl["body_other_s"] / wall, 1),
            "rpr_by_verb": {
                verb: v["rpr"] for verb, v in ctl["by_verb"].items() if v["rpr"] >= 0.01
            },
            # the sharded run's named owners (top wall-time shards)
            "top_shards": {
                shard or "-": s["reconciles"]
                for shard, s in list((ctl.get("by_shard") or {}).items())[:3]
            },
        }
    return {}


def _compact_summary(out: dict) -> dict:
    """The driver records only the tail of stdout (~2,000 chars observed:
    BENCH_r04 truncated mid-object and parsed as null). The final printed
    line must therefore be a compact selection of headline numbers; the
    full structure goes to BENCH_DETAIL.json next to this script."""
    details = out.get("details", {})
    fa = details.get("flash_attention_8k", {})
    scaling = details.get("flash_attention_scaling", {})
    scale_http = out.get("scale_http_transport", {})
    compact = {
        "metric": out["metric"],
        "value": out["value"],
        "unit": out["unit"],
        "vs_baseline": out["vs_baseline"],
        "vs_baseline_kind": out["vs_baseline_kind"],
        "http_transport_s": out.get("http_transport_s"),
        "chaos_converge_s": out.get("chaos_converge_s"),
        "placement_time_to_place_s": out.get("placement", {}).get("time_to_place_s"),
        "placement_fragmentation": out.get("placement", {}).get("fragmentation"),
        "burnin_step_p50_ms": out.get("telemetry", {}).get("burnin", {}).get("step_p50_ms"),
        "autotune_flash_speedup": out.get("autotune", {}).get("flash", {}).get(
            "speedup_vs_default"
        ),
        "gang_straggler_ratio": out.get("telemetry", {}).get("gang", {}).get("straggler_ratio"),
        "serving_tokens_per_s_chip": out.get("serving", {}).get(
            "tokens_per_s_chip_continuous"
        ),
        "serving_continuous_vs_static": out.get("serving", {}).get(
            "continuous_vs_static_speedup"
        ),
        "serving_ttft_p99_s": out.get("serving", {}).get("decode_ttft_p99_s"),
        "pod_warm_ttft_p50_s": out.get("pods", {}).get("affinity", {}).get(
            "warm_ttft_p50_s"
        ),
        "pod_cold_ttft_p50_s": out.get("pods", {}).get("affinity", {}).get(
            "cold_ttft_p50_s"
        ),
        "pod_kv_hit_ratio": out.get("pods", {}).get("affinity", {}).get(
            "kv_hit_ratio"
        ),
        "pod_kv_handoff_bytes": out.get("pods", {}).get("disagg", {}).get(
            "handoff_bytes"
        ),
        "pod_prefill_replicas": out.get("pods", {}).get("disagg", {}).get(
            "prefill_desired"
        ),
        "pod_decode_replicas": out.get("pods", {}).get("disagg", {}).get(
            "decode_desired"
        ),
        "fleet_sim_utilization_pct": out.get("fleet_sim", {}).get(
            "defrag-aware", {}
        ).get("utilization_pct"),
        "fleet_sim_p99_place_s": {
            policy: out.get("fleet_sim", {}).get(policy, {}).get("time_to_place_p99_s")
            for policy in ("best-fit", "defrag-aware")
        },
        "plan_model_ratio": out.get("fleet_sim", {}).get("model", {}).get("ratio"),
        "compile_warm_ttft_s": out.get("compile", {}).get("compile_warm_ttft_s"),
        "compile_cold_ttft_s": out.get("compile", {}).get("compile_cold_ttft_s"),
        "compile_cache_hit_ratio": out.get("compile", {}).get(
            "compile_cache_hit_ratio"
        ),
        "predict_planned_lost_steps": out.get("predict", {}).get(
            "planned_lost_steps"
        ),
        "predict_unplanned_lost_steps": out.get("predict", {}).get(
            "unplanned_lost_steps"
        ),
        "predict_false_positive_migrations": out.get("predict", {}).get(
            "false_positive_migrations"
        ),
        "tenancy_small_p99_s": {
            mode: out.get("tenancy", {}).get(mode, {}).get("tenants", {}).get(
                "small", {}
            ).get("time_to_place_p99_s")
            for mode in ("unweighted", "fair")
        },
        "tenancy_util_delta_pct": (
            round(
                out["tenancy"]["fair"]["utilization_pct"]
                - out["tenancy"]["stock"]["utilization_pct"],
                2,
            )
            if "fair" in out.get("tenancy", {}) and "stock" in out.get("tenancy", {})
            else None
        ),
        "tenancy_gold_steady_share_pct": out.get("tenancy", {}).get(
            "weights", {}
        ).get("tenants", {}).get("gold", {}).get("steady_share_pct"),
        "scale_64node_s": out.get("scale_64node_s"),
        "scale_256node_s": out.get("scale_256node_s"),
        "scale_1024node_s": out.get("scale_1024node_s"),
        "scale_4096node_s": out.get("scale_4096node_s"),
        "scale_16384node_s": out.get("scale_16384node_s"),
        "requests_per_reconcile": {
            label.replace("node_cached", ""): blk.get("requests_per_reconcile")
            for label, blk in scale_http.items()
            if label.endswith("_cached") and isinstance(blk, dict)
        },
        # the flat-write-rate series: steady writes per admin flip at
        # each cached scale (O(changes) in its purest form)
        "steady_writes_per_flip": {
            label.replace("node_cached", ""): (blk.get("steady") or {}).get("writes_per_flip")
            for label, blk in scale_http.items()
            if label.endswith("_cached") and isinstance(blk, dict)
        },
        # condensed attribution headline: the primary controller at the
        # largest traced scale — where its reconcile wall time and its
        # requests go (full per-controller blocks in BENCH_DETAIL.json)
        "attribution": _compact_attribution(out.get("attribution") or {}),
        "platform": details.get("platform"),
        "matmul_bf16_tflops": details.get("matmul_bf16_tflops")
        or details.get("matmul_bf16_tflops_lower_bound"),
        "matmul_int8_tops": details.get("matmul_int8_tops")
        or details.get("matmul_int8_tops_lower_bound"),
        "triad_gbps": details.get("triad_gbps") or details.get("triad_gbps_lower_bound"),
        "flash_8k_tflops": fa.get("tflops"),
        "flash_8k_fwd_bwd_ms": fa.get("fwd_bwd_ms"),
        "flash_32k_tflops": scaling.get("32k", {}).get("tflops"),
        "detail_file": "BENCH_DETAIL.json",
    }
    return {k: v for k, v in compact.items() if v not in (None, {})}


def scale_smoke() -> int:
    """CI gate (scripts/ci.sh): the steady-state requests-per-reconcile
    rate AND the steady-state write rate must stay flat from the small
    scale to the large one — the O(changes) property of the sharded
    control plane. Default scales are 1,024 → 16,384 sim nodes (the
    acceptance gate rpr[16384] <= 1.5 x rpr[1024]); the env override
    ``TPUOP_SCALE_SMOKE_NODES="256,1024"`` runs a compressed pair — how
    ci.sh's TPUOP_RACECHECK=1 leg keeps instrumented-lock overhead
    bounded, same convention as the compressed chaos soak. Fails
    (exit 1) when the large scale's rate exceeds 1.5 x the small one's,
    the regression shape a reintroduced full-scan or full-object write
    produces, or when writes-per-flip stops being flat. Above 1,024
    nodes the cluster sim skips per-pod materialization (kubelet
    bookkeeping, not control-plane cost — DaemonSet availability is
    simulated either way)."""
    sizes_env = os.environ.get("TPUOP_SCALE_SMOKE_NODES", "1024,16384")
    sizes = [int(s) for s in sizes_env.split(",") if s.strip()]
    lo, hi = min(sizes), max(sizes)
    results = {}
    for nodes in (lo, hi):
        elapsed, stats = bench_install_to_ready(
            nodes=nodes, transport="http", cached_reads=True,
            collect_stats=True,
            deadline_s=max(180.0, nodes * 0.06),
            settle_s=1.0,
            sim_pods=nodes <= 1024,
        )
        results[nodes] = {
            "install_to_ready_s": round(elapsed, 3),
            "requests_per_reconcile": stats["requests_per_reconcile"],
            "steady": stats["steady"],
        }
    rpr_lo = results[lo]["requests_per_reconcile"]
    rpr_hi = results[hi]["requests_per_reconcile"]
    wpf_lo = results[lo]["steady"]["writes_per_flip"]
    wpf_hi = results[hi]["steady"]["writes_per_flip"]
    # max(x, 1.0)/max(x, 2.0) keep near-zero small-scale rates from
    # flagging integer noise
    rpr_ok = rpr_hi <= 1.5 * max(rpr_lo, 1.0)
    writes_ok = wpf_hi <= 1.5 * max(wpf_lo, 2.0)
    violations = []
    if os.environ.get("TPUOP_RACECHECK") == "1":
        # the racecheck leg: every instrumented lock ran under the
        # harness for the whole run — any lock-order cycle or mutation-
        # tripwire hit fails the gate
        from tpu_operator.kube import racecheck

        violations = [repr(v) for v in racecheck.violations()]
    ok = rpr_ok and writes_ok and not violations
    print(json.dumps({
        "metric": "scale_smoke_requests_per_reconcile",
        f"rpr_{lo}": rpr_lo,
        f"rpr_{hi}": rpr_hi,
        "threshold": round(1.5 * max(rpr_lo, 1.0), 2),
        f"writes_per_flip_{lo}": wpf_lo,
        f"writes_per_flip_{hi}": wpf_hi,
        "racecheck_violations": violations,
        "ok": ok,
        "detail": results,
    }, separators=(",", ":")))
    return 0 if ok else 1


def bench_chaos_converge(
    nodes: int = 16,
    seed: int = 20260803,
    outage_at: float = 3.0,
    outage_duration: float = 30.0,
    watch_drop_every: float = 10.0,
    deadline_s: float = 240.0,
    rate_scale: float = 1.0,
    director=None,
):
    """Install→Ready under the STANDARD seeded fault schedule (5% 5xx,
    429+Retry-After bursts, 410s, connection resets, periodic watch
    drops, one full-outage window) — the chaos twin of the clean-install
    headline. Returns (elapsed_s, director) so callers can assert the
    fault classes that actually fired."""
    from tpu_operator.kube.chaos import ChaosDirector

    if director is None:
        director = ChaosDirector.standard(
            seed, outage_at=outage_at, outage_duration=outage_duration,
            watch_drop_every=watch_drop_every, rate_scale=rate_scale,
        )
    elapsed = bench_install_to_ready(
        nodes=nodes, transport="http", deadline_s=deadline_s, chaos=director
    )
    return elapsed, director


def chaos_smoke() -> int:
    """Bounded CI gate (scripts/ci.sh): the operator must converge to
    Ready through the standard fault schedule with a short outage, and
    every configured fault class must actually have fired (a schedule
    that silently injects nothing would make the gate vacuous)."""
    from tpu_operator.kube.chaos import (
        FAULT_410,
        FAULT_RESET,
        FAULT_RESET_BODY,
        ChaosDirector,
        FaultRule,
    )

    # the outage opens almost immediately so the install is FORCED to
    # ride through it (a fast clean install would otherwise finish
    # before the window). The RARE classes (410, resets) are prepended
    # as scripted fire-exactly-N rules so the gate is deterministic —
    # purely probabilistic low rates left the class coverage to luck
    # (post-PR3 installs read through informer watches, so unary GET
    # traffic is sparse) and the gate flaked red.
    director = ChaosDirector.standard(
        20260803, outage_at=0.5, outage_duration=3.0, watch_drop_every=1.0,
        rate_scale=3.0,
    )
    # GET-scoped: a scripted reset landing on the one CR-create POST
    # would fail the install's first write instead of testing recovery
    director.rules = [
        FaultRule(FAULT_410, rate=1.0, times=2, verbs=("GET",)),
        FaultRule(FAULT_RESET, rate=1.0, times=2, verbs=("GET",)),
        FaultRule(FAULT_RESET_BODY, rate=1.0, times=2, verbs=("GET",)),
        *director.rules,
    ]
    elapsed, director = bench_chaos_converge(
        nodes=32, deadline_s=120.0, director=director,
    )
    missed = director.configured_classes() - director.fired_classes()
    out = {
        "metric": "chaos_smoke_converge",
        "chaos_converge_s": round(elapsed, 3),
        "faults_injected": len(director.fault_log),
        "fault_classes": sorted(director.fired_classes()),
        "fault_classes_missed": sorted(missed),
        "seed": director.seed,
        "ok": not missed,
    }
    print(json.dumps(out, separators=(",", ":")))
    return 0 if not missed else 1


def trace_smoke() -> int:
    """CI gate (scripts/ci.sh): the flight recorder must tell the truth
    under fire and stay bounded at scale. Three checks:

    1. Install→Ready through the standard chaos schedule (plus scripted
       PATCH 500s so retries deterministically land inside reconciles):
       EVERY completed reconcile trace must be complete (no orphan
       spans, parentage intact, nothing dropped), its components must
       account for ≥95% of its measured wall time, and at least one
       retried request must appear as attempt children under one
       logical api span.
    2. The ring buffer provably wraps: capacity+N traces leave exactly
       capacity held.
    3. The 4096-node sim: traces keep being produced, the ring never
       exceeds capacity, and the measured byte estimate stays under a
       fixed cap — the memory-bounded property is measured, not assumed.
    """
    from tpu_operator import consts as _consts
    from tpu_operator.kube import trace as trace_mod
    from tpu_operator.kube.chaos import FAULT_500, ChaosDirector, FaultRule

    # 1: chaos run with full tracing
    rec = trace_mod.reset_recorder()
    attr = TraceAttribution()
    rec.add_listener(attr)
    director = ChaosDirector.standard(
        20260803, outage_at=0.5, outage_duration=3.0, watch_drop_every=2.0,
    )
    # PATCH faults land inside reconcile spans by construction (all
    # PATCHes are operator writes), so the retried-request check can't
    # flake on where the probabilistic schedule happens to hit
    director.rules = [
        FaultRule(FAULT_500, rate=1.0, times=3, verbs=("PATCH",)),
        *director.rules,
    ]
    elapsed, director = bench_chaos_converge(
        nodes=16, deadline_s=120.0, director=director,
    )
    chaos_ok = (
        attr.traces > 0
        and attr.incomplete == 0
        and attr.min_accounted >= 0.95
        and attr.retried_api_calls >= 1
    )

    # 2: the ring provably wraps
    ring = trace_mod.FlightRecorder(capacity=16)
    for i in range(16 + 8):
        t = trace_mod.Trace(
            trace_mod.Span(f"t{i}", f"t{i}", None, "reconcile", {}), 8
        )
        t.root.end = t.root.start
        ring.record(t)
    ring_ok = len(ring) == 16 and ring.traces_recorded == 24

    # 3: memory bound under the 4096-node sim (in-proc transport — the
    # FakeClient opens the same api spans, and the sim's own untraced
    # traffic proves the zero-cost path at volume)
    rec4k = trace_mod.reset_recorder()
    attr4k = TraceAttribution()
    rec4k.add_listener(attr4k)
    sim_error = None
    try:
        sim_elapsed = bench_install_to_ready(nodes=4096, deadline_s=300.0)
    except RuntimeError as e:
        sim_elapsed, sim_error = None, str(e)
    byte_cap = 8_000_000
    bound_ok = (
        sim_error is None
        and attr4k.traces > 0
        and attr4k.incomplete == 0
        and len(rec4k) <= _consts.FLIGHT_RECORDER_CAPACITY
        and rec4k.byte_estimate() <= byte_cap
    )

    ok = chaos_ok and ring_ok and bound_ok
    print(json.dumps({
        "metric": "trace_smoke",
        "ok": ok,
        "chaos": {
            "converge_s": round(elapsed, 3),
            "traces": attr.traces,
            "incomplete_traces": attr.incomplete,
            "min_accounted_pct": round(100 * attr.min_accounted, 1),
            "retried_api_calls": attr.retried_api_calls,
            "faults_injected": len(director.fault_log),
            "ok": chaos_ok,
        },
        "ring_wraps": ring_ok,
        "sim_4096": {
            "install_to_ready_s": round(sim_elapsed, 3) if sim_elapsed else None,
            "error": sim_error,
            "traces": attr4k.traces,
            "traces_held": len(rec4k),
            "capacity": _consts.FLIGHT_RECORDER_CAPACITY,
            "byte_estimate": rec4k.byte_estimate(),
            "byte_cap": byte_cap,
            "ok": bound_ok,
        },
    }, separators=(",", ":")))
    return 0 if ok else 1


def telemetry_block() -> dict:
    """The data-plane telemetry layer measured for real: a short burn-in
    under the step-time recorder (compile-vs-execute split, jitter
    percentiles, achieved TFLOP/s on whatever backend is present) and —
    when the toolchain supports multi-process CPU collectives — the live
    2-worker gang's merged artifact with its straggler ratio."""
    out: dict = {}
    try:
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh, run_burnin

        result = run_burnin(
            mesh=make_mesh(), steps=6,
            cfg=BurninConfig(d_model=128, d_ff=256, seq_len=64, batch=8, n_layers=2),
            record_telemetry=True, telemetry_host="bench",
        )
        t = result["telemetry"]
        out["burnin"] = {
            "steps": t["steps"],
            "compile_s": round(t["compile_s"], 3),
            "step_p50_ms": round(t["step_p50_s"] * 1e3, 2),
            "step_p99_ms": round(t["step_p99_s"] * 1e3, 2),
            "tflops": t.get("tflops"),
        }
    except Exception as e:  # noqa: BLE001 — best-effort like every detail
        out["burnin"] = {"error": str(e)[-300:]}
    try:
        from tpu_operator.workloads.multiproc import (
            CpuCollectivesUnsupportedError,
            run_multiprocess_check,
        )

        try:
            gang = run_multiprocess_check(num_workers=2, devices_per_worker=2)
            out["gang"] = gang.get("gang_telemetry") or {}
        except CpuCollectivesUnsupportedError:
            out["gang"] = {"skipped": "jaxlib CPU backend lacks multiprocess collectives"}
    except Exception as e:  # noqa: BLE001
        out["gang"] = {"error": str(e)[-300:]}
    return out


def bench_fleet_sim(seed: int = 20260804, hosts_dims=(16, 16, 16)) -> dict:
    """Capacity planning measured (ISSUE 15): the fleet simulator's
    best-fit vs defrag-aware comparison at 4096 sim hosts under the
    seeded churn schedule, plus the analytical model validated
    calibrate-then-predict against the recorded step-time artifacts
    (the PR 7 recorder's own output from this run). The CPU-sim series
    gate at CPU_SIM_TOLERANCE_FACTOR (3x); the 1.5x gate is reserved
    for real TPU, the PR 13 only-binds-on-TPU convention."""
    from tpu_operator.kube.sim import GangChurnSchedule
    from tpu_operator.planning.sim import FleetSimulator

    def schedule():
        # sized to press the 4096-host torus to ~75-90% mid-run so the
        # policies actually differentiate: big gangs must WAIT for
        # capacity, and what they wait on is fragmentation
        return GangChurnSchedule(
            seed=seed, ticks=120, arrivals_per_tick=2.2,
            shapes=(
                ((2, 2, 2), 4.0), ((4, 2, 2), 3.0), ((4, 4, 2), 2.0),
                ((4, 4, 4), 1.2), ((8, 4, 4), 0.5),
            ),
            min_lifetime=40, max_lifetime=110,
        )

    out: dict = {"seed": seed, "hosts": hosts_dims[0] * hosts_dims[1] * hosts_dims[2]}
    for policy in ("best-fit", "defrag-aware"):
        t0 = time.perf_counter()
        # sim ticks are coarse (one tick ~ a whole live placement pass
        # + cooldown window), so the background half runs every idle
        # tick here; the live controller's wall-clock knobs stay at the
        # conservative consts.DEFRAG_* values
        sim = FleetSimulator(
            dims=hosts_dims, policy=policy,
            migration_cooldown_ticks=2, defrag_every=1,
        )
        report = sim.run(schedule(), drain_ticks=30)
        report["sim_wall_s"] = round(time.perf_counter() - t0, 1)
        out[policy] = report
    out["model"] = _model_validation_block()
    return out


def _model_validation_block() -> dict:
    """Calibrate the analytical model on one recorded burn-in artifact,
    predict a DIFFERENT burn-in shape, and compare against what the
    recorder measured for it — the SCALE-Sim-style validation loop run
    on whatever backend is present."""
    from tpu_operator.planning.model import (
        CPU_SIM_TOLERANCE_FACTOR,
        TPU_TOLERANCE_FACTOR,
        calibrated_roofs,
        effective_compute_roof,
        predict_step_time,
        validate_prediction,
    )
    from tpu_operator.workloads.descriptor import burnin_descriptor

    try:
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh, run_burnin

        def measure(cfg):
            result = run_burnin(
                mesh=make_mesh(), steps=6, cfg=cfg,
                record_telemetry=True, telemetry_host="bench",
            )
            return result["telemetry"]

        import jax

        platform = jax.devices()[0].platform
        generation = "v5e"  # the calibration row; CPU overrides the roof anyway
        tolerance = (
            TPU_TOLERANCE_FACTOR if platform == "tpu" else CPU_SIM_TOLERANCE_FACTOR
        )
        # probe = calibration x2 along the LAYER axis: FLOPs double
        # exactly and the per-layer launch overhead amortizes the same
        # way, so the linear roofline is the right model on CPU too —
        # scaling d_model instead would mostly measure dispatch overhead
        # at these sim sizes and bias the ratio against the prediction
        cal_cfg = BurninConfig(d_model=128, d_ff=256, seq_len=64, batch=8, n_layers=2)
        probe_cfg = BurninConfig(d_model=128, d_ff=256, seq_len=64, batch=8, n_layers=4)
        cal_t = measure(cal_cfg)
        probe_t = measure(probe_cfg)
        cal_desc = burnin_descriptor(cal_cfg)
        probe_desc = burnin_descriptor(probe_cfg)
        chips = len(jax.devices())
        effective = effective_compute_roof(
            cal_desc, cal_t["step_p50_s"], hosts=1, chips_per_host=chips
        )
        roofs = calibrated_roofs(generation, effective)
        prediction = predict_step_time(
            probe_desc, generation, (1, 1, 1), chips_per_host=chips, roofs=roofs
        )
        verdict = validate_prediction(
            prediction.step_seconds, probe_t["step_p50_s"], tolerance
        )
        return {
            "platform": platform,
            "calibration_step_s": round(cal_t["step_p50_s"], 6),
            "measured_step_s": round(probe_t["step_p50_s"], 6),
            "predicted_step_s": round(prediction.step_seconds, 6),
            "effective_tflops_per_chip": round(effective or 0.0, 4),
            **verdict,
        }
    except Exception as e:  # noqa: BLE001 — best-effort like every detail
        return {"error": str(e)[-300:]}


TENANCY_SHAPES_4096 = (((2, 2, 2), 4.0), ((4, 2, 2), 3.0), ((4, 4, 4), 1.5))
TENANCY_SHAPES_512 = (((2, 2, 1), 4.0), ((2, 2, 2), 3.0), ((4, 2, 2), 1.5))


def _tenancy_starve_schedule(
    seed: int,
    arrivals_per_tick: float,
    tagged: bool = True,
    retag: bool = True,
    shapes=TENANCY_SHAPES_4096,
):
    """Two-tenant contention: a big org whose gangs keep their drawn
    priority, and a small team that (with retag) always files at
    priority 0. Stock priority-then-FIFO lets the big org preempt and
    starve the small team; equal guaranteed quotas bound the small
    team's wait. Tenant tags ride a separate seeded rng stream, so
    tagged=False yields the gang-for-gang identical schedule with the
    tags (and the retag that depends on them) absent — the
    single-tenant baseline; tagged=True, retag=False is the same
    schedule with tags riding along untouched — the no-quota
    byte-identity probe."""
    from tpu_operator.kube.sim import GangChurnSchedule

    s = GangChurnSchedule(
        seed=seed, ticks=100, arrivals_per_tick=arrivals_per_tick,
        shapes=shapes,
        min_lifetime=30, max_lifetime=90, priority_levels=2,
        tenants=(("big", 4.0), ("small", 1.0)) if tagged else None,
    )
    if tagged and retag:
        s.log = [
            (t, n, sh, (p if ten == "big" else 0), lf, ten)
            for (t, n, sh, p, lf, ten) in s.log
        ]
    return s


def bench_tenancy(seed: int = 20260807, hosts_dims=(16, 16, 16)) -> dict:
    """Multi-tenant fairness measured (ISSUE 20): the same seeded
    two-tenant contention schedule at 4096 sim hosts run three ways —

    - ``unweighted``: tenants tagged but no TPUQuota (stock
      priority-then-FIFO admission) — the big org's higher-priority
      gangs starve the small team;
    - ``fair``: equal guaranteed quotas (half the fleet each) — the
      DRF fair-share order bounds the small team's p99 time-to-place
      while the big org keeps borrowing the headroom the small team
      doesn't use;
    - ``stock``: the untagged gang-for-gang identical schedule — the
      single-tenant utilization baseline the fair run must not regress.

    Plus a weight-tracking drill: two tenants offering EQUAL demand
    under 3:1 quota weights and zero guarantees; the steady-state
    occupancy split (tail half of the run — the fill-from-empty
    transient starts 50/50 regardless of policy) must track the
    75/25 weight-implied split."""
    from tpu_operator.planning.sim import FleetSimulator

    hosts = hosts_dims[0] * hosts_dims[1] * hosts_dims[2]
    out: dict = {"seed": seed, "hosts": hosts}
    quotas = {"big": (1.0, hosts // 2), "small": (1.0, hosts // 2)}
    for label, q, tagged in (
        ("unweighted", None, True), ("fair", quotas, True), ("stock", None, False),
    ):
        t0 = time.perf_counter()
        sim = FleetSimulator(
            dims=hosts_dims, policy="defrag-aware",
            migration_cooldown_ticks=2, defrag_every=1, quotas=q,
        )
        report = sim.run(
            _tenancy_starve_schedule(seed, arrivals_per_tick=5.2, tagged=tagged),
            drain_ticks=25,
        )
        report["sim_wall_s"] = round(time.perf_counter() - t0, 1)
        out[label] = report

    from tpu_operator.kube.sim import GangChurnSchedule

    t0 = time.perf_counter()
    sim = FleetSimulator(
        dims=hosts_dims, policy="defrag-aware",
        migration_cooldown_ticks=2, defrag_every=1,
        quotas={"gold": (3.0, 0), "bronze": (1.0, 0)},
    )
    weights = sim.run(
        GangChurnSchedule(
            seed=seed, ticks=120, arrivals_per_tick=40.0,
            shapes=(((2, 2, 1), 4.0), ((2, 2, 2), 3.0), ((4, 2, 2), 1.5)),
            min_lifetime=20, max_lifetime=50, priority_levels=1,
            tenants=(("gold", 1.0), ("bronze", 1.0)),
        ),
        drain_ticks=25,
    )
    weights["sim_wall_s"] = round(time.perf_counter() - t0, 1)
    out["weights"] = weights
    return out


def tenant_smoke() -> int:
    """CI gate (scripts/ci.sh): fair-share admission end to end on the
    seeded two-tenant contention schedule at 512 sim hosts —

    1. without TPUQuota the big org starves the small team (its p99
       time-to-place at least doubles the fair run's, or some of its
       gangs never place at all);
    2. equal guaranteed quotas bound the small team's p99 and place
       every one of its gangs;
    3. fairness is not paid for with capacity: the fair run's fleet
       utilization stays within 2 points of the untagged single-tenant
       baseline on the gang-for-gang identical schedule;
    4. zero TPUQuota means byte-identical behavior: the tagged run with
       no quotas reproduces the untagged stock run's report exactly.

    ci.sh runs the gate twice — plain and TPUOP_RACECHECK=1."""
    from tpu_operator.planning.sim import FleetSimulator

    seed, dims = 20260807, (8, 8, 8)
    hosts = dims[0] * dims[1] * dims[2]
    quotas = {"big": (1.0, hosts // 2), "small": (1.0, hosts // 2)}

    def run(q, tagged, retag=True):
        sim = FleetSimulator(
            dims=dims, policy="defrag-aware",
            migration_cooldown_ticks=2, defrag_every=1, quotas=q,
        )
        return sim.run(
            _tenancy_starve_schedule(
                seed, arrivals_per_tick=1.8, tagged=tagged, retag=retag,
                shapes=TENANCY_SHAPES_512,
            ),
            drain_ticks=25,
        )

    unweighted = run(None, tagged=True)
    fair = run(quotas, tagged=True)
    stock = run(None, tagged=False)
    offered_small = sum(
        1
        for e in _tenancy_starve_schedule(seed, 1.8, shapes=TENANCY_SHAPES_512).log
        if e[5] == "small"
    )
    un_small = unweighted["tenants"]["small"]
    fair_small = fair["tenants"]["small"]
    # the no-quota identity pin: tags ride along, behavior does not —
    # the retag is skipped here because it rewrites priorities off the
    # tags (that IS the starvation mechanism), which the untagged
    # schedule can't reproduce
    identity = run(None, tagged=True, retag=False)
    identity.pop("tenants", None)
    checks = {
        "no_quota_identical_to_stock": identity == stock,
        "unweighted_starves_small": (
            un_small["time_to_place_p99_s"] >= 2.0 * fair_small["time_to_place_p99_s"]
            or un_small["gangs_placed"] < offered_small
        ),
        "fair_small_p99_bounded": fair_small["time_to_place_p99_s"] <= 30.0,
        "fair_places_all_small": fair_small["gangs_placed"] == offered_small,
        "fair_util_no_regress": (
            fair["utilization_pct"] >= stock["utilization_pct"] - 2.0
        ),
    }
    violations = []
    if os.environ.get("TPUOP_RACECHECK") == "1":
        from tpu_operator.kube import racecheck

        violations = [repr(v) for v in racecheck.violations()]
    checks["racecheck_clean"] = not violations
    ok = all(checks.values())
    print(json.dumps({
        "metric": "tenant_smoke",
        "ok": ok,
        "checks": checks,
        "small_p99_unweighted_s": un_small["time_to_place_p99_s"],
        "small_p99_fair_s": fair_small["time_to_place_p99_s"],
        "small_placed_unweighted": un_small["gangs_placed"],
        "small_placed_fair": fair_small["gangs_placed"],
        "small_offered": offered_small,
        "utilization_fair_pct": fair["utilization_pct"],
        "utilization_stock_pct": stock["utilization_pct"],
        "racecheck_violations": violations,
    }, separators=(",", ":")))
    return 0 if ok else 1


def fabric_block() -> dict:
    """The fabric probe measured for real on the virtual 8-device mesh:
    per-edge transfer bandwidth and the per-axis allreduce latency
    matrix of a 2x4x1 block with wrap links — mechanical numbers on
    CPU, physical ones on a slice; either way the sweep itself (edge
    enumeration, shard_map axis collectives, numerics check) runs."""
    try:
        from tpu_operator.workloads.fabric import run_fabric_probe

        probe = run_fabric_probe("2x4x1", wrap=True, size_mb=0.5, iters=3)
        bws = sorted(m["bw_gbps"] for m in probe["edges"].values())
        return {
            "shape": probe["shape"],
            "platform": probe["platform"],
            "edges": len(probe["edges"]),
            "min_edge_gbps": bws[0],
            "median_edge_gbps": bws[len(bws) // 2],
            "axis_allreduce_us": probe["axis_allreduce_us"],
        }
    except Exception as e:  # noqa: BLE001 — best-effort like every detail
        return {"error": str(e)[-300:]}


def fabric_smoke() -> int:
    """CI gate (scripts/ci.sh): edge-aware blame end to end on a seeded
    sim — the decision the fabric layer exists to make. A placed 8-host
    gang publishes a fabric matrix with one degraded edge; the gate
    demands:

    1. the analyzer localizes the LINK (records it in the link-health
       map; neither endpoint host is labelled or cordoned),
    2. the straddling gang re-places AROUND the cut edge — and both
       endpoint hosts remain schedulable (one may well stay in the
       gang; only the pairing is forbidden),
    3. a second matrix with multiple degraded edges sharing one
       endpoint indicts the HOST: perf label set, grey-failure FSM
       entered, gang re-places off it,
    4. the ``tpu_operator_ici_link_*`` series are live on the scrape
       endpoint, and a drained pool takes its series away.
    """
    import prometheus_client

    from tpu_operator import consts as _consts
    from tpu_operator.api.clusterpolicy import new_cluster_policy
    from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, new_tpu_slice
    from tpu_operator.controllers.health_controller import HealthReconciler
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.sim import make_torus_nodes
    from tpu_operator.placement.engine import PlacementPhase
    from tpu_operator.workloads.fabric import (
        edge_key,
        enumerate_block_edges,
        gang_fabric_artifact,
    )
    from tpu_operator.agents.slice_manager_agent import SliceManagerAgent

    ns = "tpu-operator"
    store = FakeClient()
    checks: dict = {}

    # a 16-host v4 pool; the gang needs 8, so re-placing around a cut
    # edge (and later off a blamed host) always has somewhere to go
    for node in make_torus_nodes((4, 4, 1), prefix="fab"):
        node["metadata"]["labels"][_consts.TPU_PRESENT_LABEL] = "true"
        store.create(node)
    store.create(new_cluster_policy(spec={
        "healthMonitor": {
            "interval": 1,
            "remediation": {"enable": True, "retryLimit": 3,
                            "timeoutSeconds": 300, "gracePeriodSeconds": 0},
        },
    }))
    store.create(new_tpu_slice("fab-gang", {"placement": {"shape": "2x4x1"}}))

    placement = PlacementReconciler(store, ns)
    placement.reconcile(QUEUE_REQUEST)
    slice_mgr = SliceManagerAgent(store, ns)
    slice_mgr.reconcile_once()

    def gang() -> tuple:
        ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "fab-gang")
        st = (ts.get("status") or {}).get("placement") or {}
        return list(st.get("nodes") or []), st.get("phase")

    def publish_matrix(hosts, slow_edges) -> dict:
        """A synthetic fabric matrix over the CURRENT block: uniform
        40 GB/s with the named host-pair edges at a tenth of that."""
        edges = {}
        for at, to, axis, wrap in enumerate_block_edges((2, 4, 1), wrap=True):
            key = edge_key("-".join(map(str, at)), "-".join(map(str, to)))
            edges[key] = {"bw_gbps": 40.0, "axis": axis, "wrap": wrap}
        probe = {"shape": "2x4x1", "edges": edges, "axis_allreduce_us": {"y": 210.0}}
        artifact = gang_fabric_artifact(probe, hosts)
        for edge in slow_edges:
            artifact["edges"][edge]["bw_gbps"] = 4.0
        ordered = sorted(artifact["edges"].items(), key=lambda kv: kv[1]["bw_gbps"])
        artifact["worst_edge"] = ordered[0][0]
        assert slice_mgr.publish_gang_fabric("tpu-slice-fab-gang", artifact)
        return artifact

    members, phase = gang()
    checks["placed"] = phase == PlacementPhase.SCHEDULED and len(members) == 8

    # scenario 1: ONE degraded edge -> link blame, re-place around it
    # (workers 0 and 2 of a 2x4x1 block are y-axis torus neighbors)
    cut_a, cut_b = members[0], members[2]
    cut_edge = "|".join(sorted((cut_a, cut_b)))
    artifact = publish_matrix(members, [cut_edge])
    health = HealthReconciler(store, ns)
    req = Request(name="cluster-policy")
    health.reconcile(req)

    link_cm = store.get_or_none("v1", "ConfigMap", _consts.LINK_HEALTH_CONFIGMAP, ns)
    recorded = json.dumps((link_cm or {}).get("data") or {})
    checks["link_blamed"] = cut_edge in recorded

    def in_service(name: str) -> bool:
        node = store.get("v1", "Node", name)
        labels = node["metadata"].get("labels") or {}
        return (
            not node.get("spec", {}).get("unschedulable")
            and labels.get(_consts.TPU_PERF_LABEL) is None
            and not labels.get(_consts.REPAIR_STATE_LABEL)
        )

    checks["endpoints_in_service"] = in_service(cut_a) and in_service(cut_b)

    placement.reconcile(QUEUE_REQUEST)
    slice_mgr.reconcile_once()
    members2, phase2 = gang()
    checks["replaced_around_link"] = (
        phase2 == PlacementPhase.SCHEDULED
        and len(members2) == 8
        and not (cut_a in members2 and cut_b in members2)
    )
    checks["endpoints_schedulable_after"] = in_service(cut_a) and in_service(cut_b)
    events = [e.get("reason") for e in store.list("v1", "Event")]
    checks["link_event"] = "IciLinkDegraded" in events

    # scenario 2: multiple degraded edges sharing one endpoint -> HOST
    # blame, grey-failure FSM entry, gang re-places off the host
    victim = members2[1]  # worker 1: has x edge to 0 and y edge to 3
    peers = [m for m in (members2[0], members2[3]) if m != victim]
    slow = ["|".join(sorted((victim, p))) for p in peers]
    publish_matrix(members2, slow)
    health.reconcile(req)
    victim_labels = store.get("v1", "Node", victim)["metadata"].get("labels") or {}
    checks["host_blamed"] = (
        victim_labels.get(_consts.TPU_PERF_LABEL) == _consts.PERF_DEGRADED
    )
    health.reconcile(req)  # FSM entry pass
    victim_labels = store.get("v1", "Node", victim)["metadata"].get("labels") or {}
    checks["fsm_entered"] = bool(victim_labels.get(_consts.REPAIR_STATE_LABEL))
    events = [e.get("reason") for e in store.list("v1", "Event")]
    checks["host_event"] = "IciHostDegraded" in events

    placement.reconcile(QUEUE_REQUEST)
    members3, phase3 = gang()
    checks["replaced_off_host"] = (
        phase3 == PlacementPhase.SCHEDULED
        and len(members3) == 8
        and victim not in members3
    )

    scrape = prometheus_client.generate_latest(prometheus_client.REGISTRY).decode()
    checks["series_present"] = (
        "tpu_operator_ici_link_bandwidth_gbps" in scrape
        and "tpu_operator_ici_link_degraded" in scrape
    )

    # drain the pool: every node goes, and the series must go with it
    for node in store.list("v1", "Node"):
        store.delete("v1", "Node", node["metadata"]["name"])
    health.reconcile(req)
    scrape = prometheus_client.generate_latest(prometheus_client.REGISTRY).decode()
    checks["series_removed_on_drain"] = (
        "tpu_operator_ici_link_bandwidth_gbps{" not in scrape
    )

    ok = all(checks.values())
    print(json.dumps({
        "metric": "fabric_smoke",
        "ok": ok,
        "cut_edge": cut_edge,
        "blamed_host": victim,
        "gang_initial": members,
        "gang_after_link": members2,
        "gang_after_host": members3,
        "checks": checks,
    }, separators=(",", ":")))
    return 0 if ok else 1


def telemetry_smoke() -> int:
    """CI gate (scripts/ci.sh): the grey-failure pipeline end to end on a
    seeded sim. One gang member's matmul probe runs 30% below the
    generation floor; the gate demands the whole chain fire:

    1. the exporter's sustained-breach detection flips
       ``tpu_exporter_perf_degraded`` and labels the node,
    2. the gang's published step-time artifact reads as a straggler and
       the fleet aggregation emits the PerfDegraded Event + gang series,
    3. the health FSM walks the grey node cordon -> revalidate (and,
       once the probe recovers, uncordons it clean),
    4. the placement engine re-places the gang off the degraded host,
    5. every new series is live on the scrape endpoints.
    """
    import prometheus_client

    from tpu_operator import consts as _consts
    from tpu_operator.agents.metrics_exporter_agent import MetricsExporterAgent
    from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
    from tpu_operator.api.clusterpolicy import (
        CLUSTER_POLICY_API_VERSION,
        CLUSTER_POLICY_KIND,
        new_cluster_policy,
    )
    from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, new_tpu_slice
    from tpu_operator.controllers.health_controller import HealthReconciler, RepairState
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.objects import new_object
    from tpu_operator.kube.sim import make_torus_nodes
    from tpu_operator.perf import default_floors
    from tpu_operator.placement.engine import PlacementPhase
    from tpu_operator.upgrade.fsm import DRIVER_POD_COMPONENT, DRIVER_POD_COMPONENT_LABEL
    from tpu_operator.workloads.telemetry import (
        StepTimeRecorder,
        merge_gang_reports,
        publish_prometheus,
    )

    ns = "tpu-operator"
    store = FakeClient()
    checks: dict = {}

    # an 8-host v4 pool; the gang needs 4, so a re-place off one sick
    # host always has somewhere to go
    for node in make_torus_nodes((4, 2, 1), prefix="tel"):
        node["metadata"]["labels"][_consts.TPU_PRESENT_LABEL] = "true"
        store.create(node)
    store.create(new_cluster_policy(spec={
        "healthMonitor": {
            "interval": 1,
            "remediation": {"enable": True, "retryLimit": 3,
                            "timeoutSeconds": 300, "gracePeriodSeconds": 0},
        },
    }))
    store.create(new_tpu_slice("smoke-gang", {"placement": {"shape": "2x2x1"}}))

    placement = PlacementReconciler(store, ns)
    placement.reconcile(QUEUE_REQUEST)

    def gang_nodes() -> list:
        ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "smoke-gang")
        st = (ts.get("status") or {}).get("placement") or {}
        return list(st.get("nodes") or []), st.get("phase")

    assigned, phase = gang_nodes()
    checks["placed"] = phase == PlacementPhase.SCHEDULED and len(assigned) == 4
    slow = assigned[0] if assigned else "tel-0"

    slice_mgr = SliceManagerAgent(store, ns)
    slice_mgr.reconcile_once()
    gang_cm_name = None
    for cm in store.list("v1", "ConfigMap", ns):
        if cm["metadata"]["name"].endswith("-gang"):
            gang_cm_name = cm["metadata"]["name"]
    slice_name = (gang_cm_name or "")[: -len("-gang")] if gang_cm_name else ""
    checks["gang_materialized"] = bool(gang_cm_name)

    # per-host step telemetry, REAL wall timings: the slow host's step
    # sleeps 4x longer, so the merged artifact must read straggler
    exporter_registry = prometheus_client.CollectorRegistry()
    reports = {}
    for name in assigned:
        rec = StepTimeRecorder(host=name)
        delay = 0.004 if name == slow else 0.001
        rec.run(lambda d=delay: time.sleep(d), 4)
        report = rec.report()
        reports[name] = report.to_dict()
        publish_prometheus(report, name, registry=exporter_registry)
    artifact = merge_gang_reports(reports)
    checks["straggler_detected"] = (
        artifact["straggler_ratio"] > _consts.GANG_STRAGGLER_RATIO
        and artifact["slowest_host"] == slow
    )
    checks["artifact_published"] = slice_mgr.publish_gang_telemetry(slice_name, artifact)

    # the exporter fleet: every gang member probes; the slow host's
    # matmul lands 30% BELOW the generation floor, sustained
    floor = default_floors()["v4"]["matmul_tflops"]
    roof = floor / 0.7
    exporters = {
        name: MetricsExporterAgent(
            node_name=name, client=store, registry=exporter_registry,
            floors={"matmul_tflops": floor},
        )
        for name in assigned
    }
    for _ in range(_consts.PERF_BREACH_SAMPLES):
        for name, exporter in exporters.items():
            exporter.observe_probe(
                "matmul_tflops", floor * 0.7 if name == slow else roof
            )
    slow_labels = store.get("v1", "Node", slow)["metadata"].get("labels") or {}
    checks["perf_label_set"] = (
        slow_labels.get(_consts.TPU_PERF_LABEL) == _consts.PERF_DEGRADED
    )

    # health pass: fleet aggregation (gang series + PerfDegraded event)
    # and the grey-failure FSM entry
    health = HealthReconciler(store, ns)
    req = Request(name="cluster-policy")

    def repair_state() -> str:
        labels = store.get("v1", "Node", slow)["metadata"].get("labels") or {}
        return labels.get(_consts.REPAIR_STATE_LABEL, "")

    def play_kubelet() -> None:
        # finalize evictions; keep a Running driver pod on the slow node
        # so the reinstall step can complete (the drill's kubelet/DS
        # duties, inlined — bench cannot import tests/)
        for pod in store.list("v1", "Pod", ns):
            md = pod["metadata"]
            if md.get("deletionTimestamp"):
                try:
                    store.delete("v1", "Pod", md["name"], ns, grace_period_seconds=0)
                except Exception:  # noqa: BLE001
                    pass
        if store.get_or_none("v1", "Pod", "driver-smoke", ns) is None:
            pod = new_object(
                "v1", "Pod", "driver-smoke", ns,
                labels={DRIVER_POD_COMPONENT_LABEL: DRIVER_POD_COMPONENT},
                spec={"nodeName": slow, "containers": [{"name": "d", "image": "pause:3.9"}]},
            )
            pod["status"] = {"phase": "Running"}
            store.create(pod)

    play_kubelet()
    states_seen = []
    recovered = False
    for _ in range(40):
        health.reconcile(req)
        placement.reconcile(QUEUE_REQUEST)
        slice_mgr.reconcile_once()
        play_kubelet()
        state = repair_state()
        if state and (not states_seen or states_seen[-1] != state):
            states_seen.append(state)
        if state == RepairState.REVALIDATE_REQUIRED and not recovered:
            # the reinstall "fixed" the chip: probes recover, the
            # exporter clears the label, revalidation may pass
            exporters[slow].observe_probe("matmul_tflops", roof)
            recovered = True
        if recovered and not state:
            break
    checks["fsm_cordon_to_revalidate"] = (
        RepairState.CORDON_REQUIRED in states_seen
        and RepairState.REVALIDATE_REQUIRED in states_seen
    )
    final_node = store.get("v1", "Node", slow)
    checks["repair_completed"] = (
        repair_state() == ""
        and not final_node.get("spec", {}).get("unschedulable")
        and (final_node["metadata"].get("labels") or {}).get(_consts.TPU_PERF_LABEL) is None
    )
    assigned_after, phase_after = gang_nodes()
    checks["replaced_off_slow_host"] = (
        phase_after == PlacementPhase.SCHEDULED
        and len(assigned_after) == 4
        and slow not in assigned_after
    )
    events = [e.get("reason") for e in store.list("v1", "Event")]
    checks["perf_degraded_event"] = "PerfDegraded" in events

    scrape_exporter = prometheus_client.generate_latest(exporter_registry).decode()
    scrape_operator = prometheus_client.generate_latest(prometheus_client.REGISTRY).decode()
    required_exporter = (
        "tpu_exporter_perf_degraded", "tpu_exporter_perf_floor",
        "tpu_exporter_probe_baseline", "tpu_exporter_workload_step_seconds",
        "tpu_exporter_workload_compile_seconds",
    )
    required_operator = (
        "tpu_operator_gang_step_seconds", "tpu_operator_gang_straggler_ratio",
        "tpu_operator_fleet_healthy_tflops", "tpu_operator_perf_degraded_nodes",
    )
    checks["series_present"] = all(
        s in scrape_exporter for s in required_exporter
    ) and all(s in scrape_operator for s in required_operator)

    ok = all(checks.values())
    print(json.dumps({
        "metric": "telemetry_smoke",
        "ok": ok,
        "slow_host": slow,
        "straggler_ratio": artifact["straggler_ratio"],
        "fsm_states_seen": states_seen,
        "gang_before": assigned,
        "gang_after": assigned_after,
        "checks": checks,
    }, separators=(",", ":")))
    return 0 if ok else 1


def autotune_block() -> dict:
    """The kernel-autotune sweep measured for real on the local backend:
    the flash (block_q, block_k) grid and the matmul chain-tiling grid,
    with the hardcoded default config measured INSIDE the same sweep so
    'tuned >= default' is an apples-to-apples comparison (the winner is
    the argmax over a grid containing the default, so equality means
    the default is proven already-optimal, never that tuning lost).
    Physical numbers on a chip, mechanical ones on CPU interpret mode —
    either way the harness (grid, pruning, two-point timing, winner
    pick) runs for real."""
    import jax

    from tpu_operator.workloads.autotune import (
        DEFAULT_FLASH_BLOCK_K,
        DEFAULT_FLASH_BLOCK_Q,
        DEFAULT_MATMUL_UNROLL,
        FLASH_BLOCK_GRID,
        flash_shape_class,
        sweep_flash,
        sweep_matmul,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        seq, heads, dim, iters, reps = 8192, 8, 128, 8, 4
        flash_grid = FLASH_BLOCK_GRID
        mm_size, unrolls, mm_iters = 8192, (2, 4, 8, 16), 16
    else:
        seq, heads, dim, iters, reps = 512, 2, 64, 1, 1
        flash_grid = ((128, 128), (128, 256), (256, 256), (512, 512))
        mm_size, unrolls, mm_iters = 256, (2, 4, 8), 2

    def compare(records, winner, default_config):
        by_cfg = {tuple(sorted(r.config.items())): r for r in records}
        default = by_cfg.get(tuple(sorted(default_config.items())))
        block = {
            "default": default.to_dict() if default else None,
            "winner": winner.to_dict() if winner else None,
            "configs_measured": sum(1 for r in records if not r.pruned and not r.error),
            "configs_pruned": sum(1 for r in records if r.pruned),
        }
        if winner and default and default.rate:
            # a pruned default was already proven dominated by the probe
            # pass: the winner beat it by construction
            block["tuned_ge_default"] = default.pruned or (
                (winner.rate or 0.0) >= default.rate * 0.999
            )
            block["speedup_vs_default"] = round((winner.rate or 0.0) / default.rate, 3)
        return block

    out: dict = {"platform": "tpu" if on_tpu else jax.devices()[0].platform}
    try:
        records, winner = sweep_flash(
            seq_len=seq, heads=heads, head_dim=dim, configs=flash_grid,
            iters=iters, reps=reps,
        )
        default_cfg = {
            "block_q": min(DEFAULT_FLASH_BLOCK_Q, seq),
            "block_k": min(DEFAULT_FLASH_BLOCK_K, seq),
        }
        out["flash"] = {
            "shape_class": flash_shape_class(seq, heads, dim),
            **compare(records, winner, default_cfg),
        }
    except Exception as e:  # noqa: BLE001 — best-effort like every detail
        out["flash"] = {"error": str(e)[-300:]}
    try:
        records, winner = sweep_matmul(
            size=mm_size, unrolls=unrolls, iters=mm_iters, reps=reps,
        )
        out["matmul"] = {
            "shape_class": f"m{mm_size}",
            **compare(records, winner, {"unroll": DEFAULT_MATMUL_UNROLL}),
        }
    except Exception as e:  # noqa: BLE001
        out["matmul"] = {"error": str(e)[-300:]}
    return out


def autotune_smoke() -> int:
    """CI gate (scripts/ci.sh): the closed autotune loop end to end on a
    seeded sim with TWO generations (v4 + v5e), plus a real (tiny) sweep
    on the local backend. The gate demands:

    1. the controller elects exactly ONE in-service node per un-swept
       generation (deterministically), and the sweep runs exactly once
       per generation fleet-wide;
    2. results land in the ``tpu-autotune-results`` ConfigMap keyed by
       (generation, libtpu version), the winners blob is published, and
       the perf-floors ConfigMap tightens — the folded v5e floor within
       5% of perf.py's measured roof x FLOOR_FRACTION;
    3. the exporter hot-reloads the tightened floor (the very next
       observe_probe comparison uses it, no pod restart);
    4. a second pass is a cache hit: elections cleared, ZERO apiserver
       writes from controller and agent;
    5. a node joining an already-swept generation is never elected and
       never re-sweeps (still zero writes);
    6. workloads resolve the published winners (tuned_flash_blocks)
       and, on the real local sweep, the tuned flash config's achieved
       rate >= the hardcoded default config's.
    """
    from tpu_operator import consts as _consts
    from tpu_operator.agents.autotune_agent import AutotuneAgent
    from tpu_operator.agents.metrics_exporter_agent import MetricsExporterAgent
    from tpu_operator.api.clusterpolicy import (
        ClusterPolicy,
        new_cluster_policy,
    )
    from tpu_operator.controllers.autotune_controller import (
        AutotuneReconciler,
        libtpu_version_for,
    )
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.objects import new_object
    from tpu_operator.kube.sim import make_torus_nodes, make_tpu_node
    from tpu_operator.perf import FLOOR_FRACTION, floors_for, floors_json
    from tpu_operator.workloads.autotune import tuned_flash_blocks

    ns = "tpu-operator"
    checks: dict = {}

    class CountingClient:
        """Write-counting shim over the FakeClient: the zero-write
        steady-state checks read it."""

        WRITE_VERBS = ("create", "patch", "patch_status", "update",
                       "update_status", "delete", "apply", "apply_set")

        def __init__(self, inner):
            self._inner = inner
            self.writes = 0

        def __getattr__(self, name):
            attr = getattr(self._inner, name)
            if name in self.WRITE_VERBS and callable(attr):
                def counted(*a, **kw):
                    self.writes += 1
                    return attr(*a, **kw)

                return counted
            return attr

    store = FakeClient()
    client = CountingClient(store)
    # two generations: a 4-host v4 pool and a 2-host v5e pool
    for node in make_torus_nodes((2, 2, 1), prefix="v4a", accelerator="tpu-v4-podslice"):
        node["metadata"]["labels"][_consts.TPU_PRESENT_LABEL] = "true"
        store.create(node)
    for i in range(2):
        node = make_tpu_node(f"v5e-{i}", "tpu-v5-lite-podslice", "2x4")
        node["metadata"]["labels"][_consts.TPU_PRESENT_LABEL] = "true"
        store.create(node)
    store.create(new_cluster_policy())
    # the floors CM as pre-requisites renders it (default table)
    store.create(new_object(
        "v1", "ConfigMap", _consts.PERF_FLOORS_CONFIGMAP, ns,
        data={_consts.PERF_FLOORS_KEY: floors_json()},
    ))
    cp = ClusterPolicy.from_unstructured(store.get(
        "tpu.google.com/v1", "ClusterPolicy", "cluster-policy"
    ))
    version = libtpu_version_for(cp)
    # the autotuner DaemonSet pins LIBTPU_VERSION to the libtpu image
    # tag; the smoke's in-process agents need the same pin so their
    # recorded fingerprint matches the controller's expectation
    os.environ["LIBTPU_VERSION"] = version

    reconciler = AutotuneReconciler(client, ns)
    req = Request(name="cluster-policy")
    reconciler.reconcile(req)

    def elected_nodes() -> list:
        return sorted(
            n["metadata"]["name"] for n in store.list("v1", "Node")
            if (n["metadata"].get("labels") or {}).get(_consts.AUTOTUNE_ELECTED_LABEL)
            == _consts.AUTOTUNE_ELECTED
        )

    elected = elected_nodes()
    # lexicographically-first in-service node of each generation
    checks["one_election_per_generation"] = elected == ["v4a-0", "v5e-0"]

    # the elected agents sweep (injected sweep records TPU-measured
    # rates: v5e at its real measured roof, v4 10% under its scaled
    # guess — the fold must move BOTH floors to the measured truth)
    sweeps: dict = {}
    # v5e at its real measured roof (the 5%-of-perf.py acceptance
    # check); v4 ABOVE its scaled guess, so the folded floor tightens
    # upward and catches a shortfall the stale floor missed
    measured = {"v4": 270.0, "v5e": 185.0}

    def fake_sweep(gen, ver):
        sweeps[gen] = sweeps.get(gen, 0) + 1
        flash = {"block_q": 512, "block_k": 1024, "time_ms": 2.0,
                 "rate": 100.0, "stable": True}
        return {
            "generation": gen, "libtpu_version": ver, "platform": "tpu",
            "results": {
                "flash_fwd": {"s8192_h8_d128": {"winner": flash, "configs": [flash]}},
                "flash_fwd_bwd": {"s8192_h8_d128": {"winner": flash, "configs": [flash]}},
                "matmul": {"m8192": {"winner": {"unroll": 16, "rate": measured[gen],
                                                "stable": True}, "configs": []}},
                "int8": {"m8192": {"winner": {"unroll": 8, "rate": measured[gen] * 2,
                                              "stable": True}, "configs": []}},
            },
        }

    agents = {
        name: AutotuneAgent(client, name, ns, sweep_fn=fake_sweep)
        for name in elected
    }
    outcomes = {name: agent.reconcile_once() for name, agent in agents.items()}
    checks["sweeps_ran"] = all(o == "swept" for o in outcomes.values())

    # fold pass: elections clear, floors tighten, winners publish
    reconciler.reconcile(req)
    checks["elections_cleared"] = elected_nodes() == []
    results_cm = store.get("v1", "ConfigMap", _consts.AUTOTUNE_RESULTS_CONFIGMAP, ns)
    data = results_cm.get("data") or {}
    checks["results_cached"] = "v4.json" in data and "v5e.json" in data
    winners_raw = data.get(_consts.AUTOTUNE_WINNERS_KEY, "")
    checks["winners_published"] = '"block_q": 512' in winners_raw
    floors_cm = store.get("v1", "ConfigMap", _consts.PERF_FLOORS_CONFIGMAP, ns)
    blob = (floors_cm.get("data") or {}).get(_consts.PERF_FLOORS_KEY, "")
    folded = json.loads(blob)
    want_v5e = measured["v5e"] * FLOOR_FRACTION
    got_v5e = folded.get("v5e", {}).get("matmul_tflops", 0.0)
    checks["v5e_floor_measured"] = abs(got_v5e - want_v5e) <= 0.05 * want_v5e
    checks["v4_floor_tightened"] = (
        folded.get("v4", {}).get("matmul_tflops")
        == round(measured["v4"] * FLOOR_FRACTION, 1)
    )

    # exporter hot-reload: the tightened floor bites the VERY NEXT
    # observe_probe comparison, no pod restart
    exporter = MetricsExporterAgent(
        node_name="v4a-0", client=store, namespace=ns, generation="v4",
        floors=floors_for("v4"),  # the stale built-in table from pod start
        breach_samples=1,
    )
    stale_floor = exporter.floors["matmul_tflops"]
    probe_value = (stale_floor + folded["v4"]["matmul_tflops"]) / 2.0
    checks["hot_reload_applied"] = exporter.refresh_floors() and (
        exporter.floors["matmul_tflops"] == folded["v4"]["matmul_tflops"]
    )
    # above the stale floor, below the tightened one -> breach only
    # because the reload landed
    checks["hot_reload_bites"] = exporter.observe_probe("matmul_tflops", probe_value)

    # steady state: a third controller pass and re-run agents (now
    # descheduled — election cleared) — ZERO apiserver writes
    client.writes = 0
    reconciler.reconcile(req)
    outcomes = {name: agent.reconcile_once() for name, agent in agents.items()}
    checks["steady_agents_descheduled"] = all(o == "not-elected" for o in outcomes.values())
    checks["steady_zero_writes"] = client.writes == 0

    # a REBOOTED elected node (label re-stamped by an admin race /
    # controller lag): the valid cache entry reads as a hit — zero
    # writes, no re-sweep — and the next controller pass re-clears
    node = store.get("v1", "Node", "v4a-0")
    node["metadata"]["labels"][_consts.AUTOTUNE_ELECTED_LABEL] = _consts.AUTOTUNE_ELECTED
    store.update(node)
    client.writes = 0
    checks["reboot_cache_hit"] = agents["v4a-0"].reconcile_once() == "cache-hit"
    checks["reboot_zero_agent_writes"] = client.writes == 0
    reconciler.reconcile(req)
    checks["stale_election_cleared"] = elected_nodes() == []

    # a node joining the already-swept v4 generation (sorting FIRST, so
    # a naive re-election would pick it): never elected, never sweeps
    joiner = make_tpu_node("a-joiner", "tpu-v4-podslice", "4x4x1")
    joiner["metadata"]["labels"][_consts.TPU_PRESENT_LABEL] = "true"
    store.create(joiner)
    client.writes = 0
    reconciler.reconcile(req)
    joined_agent = AutotuneAgent(client, "a-joiner", ns, sweep_fn=fake_sweep)
    checks["joiner_not_elected"] = (
        elected_nodes() == [] and joined_agent.reconcile_once() == "not-elected"
    )
    checks["joiner_zero_writes"] = client.writes == 0
    checks["exactly_one_sweep_per_generation"] = sweeps == {"v4": 1, "v5e": 1}

    # consumption: workloads resolve the published winners
    os.environ["TPU_AUTOTUNE_JSON"] = winners_raw
    try:
        os.environ["TPU_GENERATION"] = "v4"
        checks["winners_resolved"] = tuned_flash_blocks(8192) == (512, 1024)
        # an un-swept generation falls back to the hand-swept defaults
        os.environ["TPU_GENERATION"] = "v6e"
        checks["winners_fallback"] = tuned_flash_blocks(8192) == (1024, 1024)
    finally:
        del os.environ["TPU_AUTOTUNE_JSON"]
        del os.environ["TPU_GENERATION"]
        del os.environ["LIBTPU_VERSION"]

    # the real (tiny) sweep on the local backend: tuned >= default
    block = autotune_block()
    checks["local_flash_tuned_ge_default"] = bool(
        block.get("flash", {}).get("tuned_ge_default")
    )

    ok = all(checks.values())
    print(json.dumps({
        "metric": "autotune_smoke",
        "ok": ok,
        "elected": elected,
        "v5e_floor": got_v5e,
        "v5e_roof_x_fraction": round(want_v5e, 1),
        "local_flash": block.get("flash"),
        "checks": checks,
    }, separators=(",", ":")))
    return 0 if ok else 1


def compile_block() -> dict:
    """Warm-vs-cold warm-start on the local backend: a first replica of
    a (generation, topology, model) key pays the cold XLA compile and
    publishes the measured duration; a second replica resolves the
    record and warms from the in-process executable cache. Cold is
    measured FIRST — the jit cache would otherwise hide it."""
    from tpu_operator.workloads import compilecache
    from tpu_operator.workloads.compilecache import CompileCacheStore
    from tpu_operator.workloads.serving import DecodeEngine, ServingModelConfig
    from tpu_operator.kube.fake import FakeClient

    compilecache.reset_stats()
    store = CompileCacheStore(FakeClient(), "tpu-operator", libtpu_version="bench")
    # distinct dims: this key's executables are this block's alone
    cfg = ServingModelConfig(max_seq=48)
    outcome_cold, cold_s = store.warm_start(
        DecodeEngine(cfg), "v5e", "2x4", serving="bench")
    outcome_warm, warm_s = store.warm_start(
        DecodeEngine(cfg), "v5e", "2x4", serving="bench")
    stats = compilecache.stats()
    hits = sum(stats["hits"].values())
    misses = sum(stats["misses"].values())
    return {
        "compile_cold_ttft_s": round(cold_s, 4),
        "compile_warm_ttft_s": round(warm_s, 4),
        "warm_speedup": round(cold_s / warm_s, 1) if warm_s > 0 else None,
        "compile_cache_hit_ratio": round(hits / (hits + misses), 3)
        if hits + misses else 0.0,
        "outcomes": [outcome_cold, outcome_warm],
    }


def compile_smoke() -> int:
    """CI gate (scripts/ci.sh): the fleet compile cache end to end on
    the local backend + a seeded sim. The gate demands:

    1. hit vs miss is measured, not assumed: the first replica of a key
       pays the cold compile (miss, record published), the second
       resolves the record and its measured warmup is FAR below the
       first's; a third warm start issues zero apiserver writes;
    2. the AOT prewarm handshake closes: the serving controller
       publishes a request for the uncached key (idempotently), the
       compile-cache controller elects exactly one in-service node of
       the generation, the agent compiles + acks, election and request
       both clear, and the worker that then boots starts WARM — its
       time-to-ready beats the un-prewarmed baseline;
    3. steady state (everything cached) is ZERO writes across the
       serving controller, compile-cache controller, and agent;
    4. a simulated libtpu bump deletes exactly the affected generations'
       entries and the re-prewarm compiles exactly once per generation
       with demand;
    5. planning prices the compile: the warm what-if ETA is strictly
       below the cold ETA for the same shape.
    """
    from tpu_operator import consts as _consts
    from tpu_operator.agents.compilecache_agent import (
        CompileCacheAgent,
        default_warm_fn,
    )
    from tpu_operator.api.clusterpolicy import new_cluster_policy
    from tpu_operator.api.tpuserving import TPUServing, new_tpu_serving
    from tpu_operator.controllers.autotune_controller import libtpu_version_for
    from tpu_operator.controllers.compilecache_controller import (
        CompileCacheReconciler,
    )
    from tpu_operator.controllers.serving_controller import ServingReconciler
    from tpu_operator.api.clusterpolicy import ClusterPolicy
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.sim import make_torus_nodes, make_tpu_node
    from tpu_operator.planning.model import compile_cost_seconds
    from tpu_operator.planning.whatif import admission_answer
    from tpu_operator.workloads import compilecache
    from tpu_operator.workloads.compilecache import (
        CompileCacheStore,
        cached_entries,
        entry_key,
        model_descriptor_hash,
        parse_requests,
        request_id,
    )
    from tpu_operator.workloads.serving import DecodeEngine, ServingModelConfig

    ns = "tpu-operator"
    checks: dict = {}
    compilecache.reset_stats()

    class CountingClient:
        """Write-counting shim over the FakeClient (the autotune-smoke
        pattern) plus a call log for the exactly-one-patch checks."""

        WRITE_VERBS = ("create", "patch", "patch_status", "update",
                       "update_status", "delete", "apply", "apply_set")

        def __init__(self, inner):
            self._inner = inner
            self.writes = 0
            self.calls = []

        def __getattr__(self, name):
            attr = getattr(self._inner, name)
            if name in self.WRITE_VERBS and callable(attr):
                def counted(*a, **kw):
                    self.writes += 1
                    self.calls.append((name,) + tuple(
                        x for x in a[:3] if isinstance(x, str)))
                    return attr(*a, **kw)

                return counted
            return attr

    store_fake = FakeClient()
    client = CountingClient(store_fake)
    for i in range(2):
        node = make_tpu_node(f"v5e-{i}", "tpu-v5-lite-podslice", "2x4")
        node["metadata"]["labels"][_consts.TPU_PRESENT_LABEL] = "true"
        store_fake.create(node)
    store_fake.create(new_cluster_policy())
    cp = ClusterPolicy.from_unstructured(store_fake.get(
        "tpu.google.com/v1", "ClusterPolicy", "cluster-policy"
    ))
    version = libtpu_version_for(cp)
    # the DaemonSet pins LIBTPU_VERSION; in-process stores need the same
    os.environ["LIBTPU_VERSION"] = version

    def cache_data() -> dict:
        cm = store_fake.get_or_none(
            "v1", "ConfigMap", _consts.COMPILE_CACHE_CONFIGMAP, ns)
        return (cm or {}).get("data") or {}

    # -- part 1: warm-start hit vs miss, measured ---------------------------
    # cold FIRST, on dims no other scenario compiles — the in-process
    # jit cache would otherwise hide the cold cost
    cfg_a = ServingModelConfig(max_seq=32)
    store = CompileCacheStore(client, ns)
    o1, cold_s = store.warm_start(DecodeEngine(cfg_a), "v5e", "2x4", serving="smoke")
    checks["first_replica_misses"] = o1 == "miss"
    checks["miss_published_record"] = entry_key("v5e") in cache_data()
    o2, warm_s = store.warm_start(DecodeEngine(cfg_a), "v5e", "2x4", serving="smoke")
    checks["second_replica_hits"] = o2 == "hit"
    checks["warm_ttft_beats_cold"] = warm_s < cold_s * 0.5
    client.writes = 0
    o3, _ = store.warm_start(DecodeEngine(cfg_a), "v5e", "2x4", serving="smoke")
    checks["steady_hit_zero_writes"] = o3 == "hit" and client.writes == 0

    # -- part 2: the AOT prewarm handshake ----------------------------------
    serving_obj = new_tpu_serving("svc", {
        "model": {"shape": "2x4", "generation": "v5e"},
        "minReplicas": 1, "maxReplicas": 2,
    })
    serving = TPUServing.from_unstructured(serving_obj)
    model_hash = model_descriptor_hash()
    rid = request_id("v5e", "2x4", model_hash)
    sr = ServingReconciler(client, ns)
    sr._reconcile_prewarm(serving_obj, serving, {})
    requested = parse_requests(
        cache_data().get(_consts.COMPILE_PREWARM_REQUEST_KEY))
    checks["prewarm_requested"] = rid in requested
    client.writes = 0
    sr._reconcile_prewarm(serving_obj, serving, {})
    checks["request_idempotent"] = client.writes == 0

    def elected_nodes() -> list:
        return sorted(
            n["metadata"]["name"] for n in store_fake.list("v1", "Node")
            if (n["metadata"].get("labels") or {}).get(
                _consts.COMPILE_CACHE_ELECTED_LABEL)
            == _consts.COMPILE_CACHE_ELECTED
        )

    ccr = CompileCacheReconciler(client, ns)
    req = Request(name="cluster-policy")
    ccr.reconcile(req)
    checks["one_node_elected"] = elected_nodes() == ["v5e-0"]

    warm_calls = []

    def counting_warm(request, ver):
        warm_calls.append(request.get("generation"))
        return default_warm_fn(request, ver)

    agent = CompileCacheAgent(client, "v5e-0", ns, warm_fn=counting_warm)
    checks["agent_prewarmed"] = agent.reconcile_once() == "prewarmed"
    acks = (compilecache.parse_entry(
        cache_data().get(_consts.COMPILE_PREWARM_ACK_KEY)) or {}).get("acks") or {}
    checks["agent_acked"] = rid in acks
    ccr.reconcile(req)
    checks["election_cleared"] = elected_nodes() == []
    sr._reconcile_prewarm(serving_obj, serving, {})
    checks["request_cleared"] = parse_requests(
        cache_data().get(_consts.COMPILE_PREWARM_REQUEST_KEY)) == {}

    # steady state: everything cached — zero writes anywhere
    client.writes = 0
    sr._reconcile_prewarm(serving_obj, serving, {})
    ccr.reconcile(req)
    checks["steady_agent_descheduled"] = agent.reconcile_once() == "not-elected"
    checks["steady_zero_writes"] = client.writes == 0

    # the prewarmed worker boots warm: its measured warmup is far below
    # both the agent's recorded compile and part 1's un-prewarmed cold
    agent_record = (compilecache.parse_entry(
        cache_data().get(entry_key("v5e"))) or {}).get("records", {}).get(
        f"2x4/{model_hash}") or {}
    agent_compile_s = float(agent_record.get("seconds") or 0.0)
    ow, prewarmed_ttft = store.warm_start(
        DecodeEngine(ServingModelConfig()), "v5e", "2x4", serving="svc")
    checks["prewarmed_worker_hits"] = ow == "hit"
    checks["prewarmed_beats_agent_compile"] = (
        0.0 < prewarmed_ttft < agent_compile_s * 0.5
    )
    checks["prewarmed_scaleup_beats_unprewarmed"] = prewarmed_ttft < cold_s * 0.5

    # -- part 3: libtpu bump invalidates exactly the affected entries -------
    # a second generation's record so the bump provably sweeps ALL
    # stale entries, one key-scoped patch each
    store.publish("v4", "4x4x1", "fakehash0001", 1.25, source="prewarm")
    checks["two_generations_cached"] = set(cached_entries(cache_data())) == {
        "v4", "v5e"}
    store_fake.patch(
        "tpu.google.com/v1", "ClusterPolicy", "cluster-policy",
        {"spec": {"libtpu": {"repository": "gcr.io/tpu-operator",
                             "image": "libtpu", "version": "9.9.9-smoke"}}},
    )
    os.environ["LIBTPU_VERSION"] = "9.9.9-smoke"
    client.calls = []
    ccr.reconcile(req)
    invalidation_patches = [
        c for c in client.calls
        if c[0] == "patch" and _consts.COMPILE_CACHE_CONFIGMAP in c
    ]
    checks["bump_invalidates_all_affected"] = cached_entries(cache_data()) == {}
    checks["one_patch_per_affected_generation"] = len(invalidation_patches) == 2
    # the serving's key re-requests, re-elects, re-compiles ONCE
    sr._reconcile_prewarm(serving_obj, serving, {})
    ccr.reconcile(req)
    checks["bump_reelects"] = elected_nodes() == ["v5e-0"]
    checks["bump_agent_reprewarmed"] = agent.reconcile_once() == "prewarmed"
    checks["one_recompile_per_generation"] = warm_calls == ["v5e", "v5e"]
    ccr.reconcile(req)
    sr._reconcile_prewarm(serving_obj, serving, {})
    client.writes = 0
    ccr.reconcile(req)
    sr._reconcile_prewarm(serving_obj, serving, {})
    checks["post_bump_steady_zero_writes"] = client.writes == 0

    # -- part 4: planning prices the compile --------------------------------
    warm_cost, warm_flag = compile_cost_seconds(
        "v5e", "1x1x1", "mhash", entries={
            "v5e": {"generation": "v5e", "libtpu_version": version,
                    "records": {"1x1x1/mhash": {"seconds": 2.0}}},
        }, libtpu_version=version)
    cold_cost, cold_flag = compile_cost_seconds(
        "v5e", "1x1x1", "mhash", entries={}, libtpu_version=version)
    checks["model_warm_strictly_below_cold"] = (
        warm_flag and not cold_flag and 0.0 < warm_cost < cold_cost
    )
    plan_nodes = make_torus_nodes(
        (2, 2, 1), prefix="plan", accelerator="tpu-v5-lite-podslice")
    warm_entries = {
        "v5e": {"generation": "v5e", "libtpu_version": version,
                "records": {"1x1x1/mhash": {"seconds": 2.0}}},
    }
    warm_ans = admission_answer(
        [], plan_nodes, "1x1x1",
        compile_entries=warm_entries, libtpu_version=version,
        model_hash="mhash")
    cold_ans = admission_answer(
        [], plan_nodes, "1x1x1",
        compile_entries={}, libtpu_version=version, model_hash="mhash")
    legacy_ans = admission_answer([], plan_nodes, "1x1x1")
    checks["whatif_warm_eta_strictly_below_cold"] = (
        warm_ans["answer"] == "now" and cold_ans["answer"] == "now"
        and warm_ans["eta_seconds"] < cold_ans["eta_seconds"]
    )
    checks["whatif_legacy_eta_unpriced"] = legacy_ans["eta_seconds"] == 0.0

    del os.environ["LIBTPU_VERSION"]

    violations = []
    if os.environ.get("TPUOP_RACECHECK") == "1":
        from tpu_operator.kube import racecheck

        violations = [repr(v) for v in racecheck.violations()]
    checks["racecheck_clean"] = not violations
    ok = all(checks.values())
    print(json.dumps({
        "metric": "compile_smoke",
        "ok": ok,
        "cold_ttft_s": round(cold_s, 4),
        "warm_ttft_s": round(warm_s, 4),
        "prewarmed_ttft_s": round(prewarmed_ttft, 4),
        "agent_compile_s": round(agent_compile_s, 4),
        "warm_eta_s": warm_ans.get("eta_seconds"),
        "cold_eta_s": cold_ans.get("eta_seconds"),
        "checks": checks,
        "racecheck_violations": violations,
    }, separators=(",", ":")))
    return 0 if ok else 1


def bench_placement(
    dims=(8, 8, 8),
    seed: int = 20260803,
    churn_cycles: int = 3,
    churn_fraction: float = 0.33,
):
    """Topology-aware placement over a churned 512-host torus: fill the
    pod with mixed-shape slices, then repeatedly evict a seeded random
    subset and re-place fresh requests, timing every planning pass and
    verifying the invariant that matters — zero double-booked hosts.

    Runs the REAL planning path (PlacementEngine over labelled Node
    objects, label deltas applied back like the controller would), not a
    bare allocator loop, so gang re-validation cost at steady occupancy
    is inside the measurement."""
    import math
    import random

    from tpu_operator import consts as _consts
    from tpu_operator.kube.sim import make_torus_nodes
    from tpu_operator.placement.engine import PlacementEngine, PlacementPhase

    shapes = ["4x4x4", "4x4x2", "2x2x2", "4x2x2", "2x2x1", "4x4x1"]
    rng = random.Random(seed)
    nodes = make_torus_nodes(dims)
    nodes_by_name = {n["metadata"]["name"]: n for n in nodes}
    slices: dict = {}
    serial = 0

    def new_slice(shape: str) -> str:
        nonlocal serial
        serial += 1
        name = f"bench-{serial}"
        slices[name] = {
            "apiVersion": "tpu.google.com/v1alpha1",
            "kind": "TPUSlice",
            "metadata": {"name": name, "creationTimestamp": f"T{serial:06d}"},
            "spec": {"placement": {"shape": shape, "priority": 0}},
        }
        return name

    def apply_plan(plan) -> None:
        for node_name, delta in plan.label_deltas.items():
            labels = nodes_by_name[node_name]["metadata"].setdefault("labels", {})
            for key, value in delta.items():
                if value is None:
                    labels.pop(key, None)
                else:
                    labels[key] = value
        for name, status in plan.statuses.items():
            if name in slices:
                slices[name].setdefault("status", {})["placement"] = status

    def overlap_violations() -> int:
        violations = 0
        claimed: dict = {}
        for name, obj in slices.items():
            st = (obj.get("status") or {}).get("placement") or {}
            if st.get("phase") != PlacementPhase.SCHEDULED:
                continue
            shape = [int(d) for d in obj["spec"]["placement"]["shape"].split("x")]
            assigned = st.get("nodes") or []
            if len(assigned) != math.prod(shape):
                violations += 1
            for node_name in assigned:
                if claimed.setdefault(node_name, name) != name:
                    violations += 1
                label_owner = (
                    nodes_by_name[node_name]["metadata"].get("labels") or {}
                ).get(_consts.PLACEMENT_LABEL)
                if label_owner != name:
                    violations += 1
        return violations

    def plan_once() -> tuple:
        t0 = time.perf_counter()
        plan = PlacementEngine(list(slices.values()), nodes).plan()
        elapsed = time.perf_counter() - t0
        apply_plan(plan)
        return elapsed, plan

    t_start = time.perf_counter()
    times = []
    # fill until two consecutive shapes bounce — steady high occupancy
    misses = 0
    while misses < 2:
        name = new_slice(rng.choice(shapes))
        elapsed, _ = plan_once()
        times.append(elapsed)
        st = (slices[name].get("status") or {}).get("placement") or {}
        if st.get("phase") == PlacementPhase.SCHEDULED:
            misses = 0
        else:
            misses += 1
            del slices[name]  # keep the queue to real, placeable work
            plan_once()
    violations = overlap_violations()
    # churn: evict a seeded third, re-place fresh mixed shapes
    for _ in range(churn_cycles):
        placed = sorted(
            n for n, o in slices.items()
            if ((o.get("status") or {}).get("placement") or {}).get("phase")
            == PlacementPhase.SCHEDULED
        )
        evict = rng.sample(placed, max(1, int(len(placed) * churn_fraction)))
        for name in evict:
            del slices[name]
        plan_once()  # the teardown pass (labels of deleted slices clear)
        for _ in evict:
            name = new_slice(rng.choice(shapes))
            elapsed, _ = plan_once()
            times.append(elapsed)
            st = (slices[name].get("status") or {}).get("placement") or {}
            if st.get("phase") != PlacementPhase.SCHEDULED:
                del slices[name]
                plan_once()
        violations += overlap_violations()
    scheduled = sum(
        1 for o in slices.values()
        if ((o.get("status") or {}).get("placement") or {}).get("phase")
        == PlacementPhase.SCHEDULED
    )
    frag = PlacementEngine(list(slices.values()), nodes).plan().fragmentation
    return {
        "hosts": dims[0] * dims[1] * dims[2],
        "slices_scheduled": scheduled,
        "placements_attempted": len(times),
        "time_to_place_s": round(statistics.median(times), 4),
        "time_to_place_max_s": round(max(times), 4),
        "fragmentation": max(frag.values()) if frag else 0.0,
        "overlap_violations": violations,
        "elapsed_s": round(time.perf_counter() - t_start, 3),
    }


def bench_training(seed: int = 20260811, steps: int = 120) -> dict:
    """Elastic fault-tolerant training (ISSUE 13, re-run through the
    pod data plane of ISSUE 16): one TPUJob driven through the seeded
    gang fault schedule — host death, grey failure, link cut,
    preemption — on a 2x2x1 sim torus. The job controller renders one
    worker pod per gang member; the sim kubelet runs the pod mains
    (rendezvous-gated chief training for real); every re-place rolls a
    new pod generation. Returns the BENCH ``training`` block: resume
    latency, lost steps per fault, and the shrink step-time ratio vs
    the gang-telemetry prediction (fixed global batch ⇒ step time
    scales ~ hosts_full / hosts_shrunk) — continuity verified over the
    CONCATENATED chief histories across pod generations."""
    import statistics as stats
    import tempfile

    from tpu_operator import consts
    from tpu_operator.api.tpujob import JobPhase, new_tpu_job
    from tpu_operator.controllers.job_controller import JobReconciler
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.sim import (
        GangFaultSchedule,
        PodKubelet,
        make_torus_nodes,
    )
    from tpu_operator.workloads.training import verify_continuity

    ns = "tpu-operator"
    client = FakeClient()
    for node in make_torus_nodes((2, 2, 1), prefix="bench-tj"):
        node["metadata"]["labels"]["tpu.google.com/tpu.present"] = "true"
        client.create(node)
    # the checkpoint dir is pinned so every pod generation resumes from
    # the SAME store (the spec contract real multi-pod jobs rely on)
    store_dir = tempfile.mkdtemp(prefix="bench-tpujob-")
    client.create(new_tpu_job("bench-job", {
        "workload": {"steps": steps},
        "gang": {"shape": "2x2x1", "minShape": "1x1x1"},
        "checkpoint": {"everySteps": 5, "dir": store_dir},
        "backoff": {"baseSeconds": 0.01, "maxSeconds": 0.05, "retryLimit": 10},
    }))
    job_rec = JobReconciler(client, ns)
    place_rec = PlacementReconciler(client, ns)
    kubelet = PodKubelet(client, ns)
    schedule = GangFaultSchedule(
        client, ns, "bench-job-slice", seed=seed, start_at=3, every=10, heal_after=4
    )
    t0 = time.monotonic()
    passes = 0
    for passes in range(1, 500):
        job_rec.reconcile(Request(name="bench-job"))
        place_rec.reconcile(QUEUE_REQUEST)
        kubelet.step()
        schedule.step()
        job = client.get("tpu.google.com/v1alpha1", "TPUJob", "bench-job")
        block = (job.get("status") or {}).get("job") or {}
        if block.get("phase") == JobPhase.SUCCEEDED:
            break
    elapsed = time.monotonic() - t0
    trainers = kubelet.job_trainers("bench-job")
    kubelet.stop()
    worker_pods_left = [
        p["metadata"]["name"]
        for p in client.list("v1", "Pod", ns)
        if p["metadata"]["name"].startswith("bench-job" + consts.JOB_WORKER_INFIX)
    ]
    history = [h for t in trainers for h in t.history]
    checkpoints = [c for t in trainers for c in t.checkpoints]
    total_steps = trainers[-1].total_steps if trainers else steps
    report = verify_continuity(history, checkpoints, total_steps)
    faults = len([r for r in schedule.log if r[1] == "inject"])
    # lost work: re-executed steps across every rewind
    executed = [h["step"] for h in history]
    lost = len(executed) - len(set(executed))
    resumes = []
    for gen, t in enumerate(trainers):
        latencies = [r.latency_s for r in t.resumes]
        # the first generation's [0] is the cold start; every later
        # generation's [0] is its resume-from-checkpoint under a new pod
        resumes.extend(latencies[1:] if gen == 0 else latencies)
    step_times: dict = {}
    for t in trainers:
        for world, times in t.step_times.items():
            step_times.setdefault(world, []).extend(times)
    # shrink step-time ratio: median executed-step time per world (first
    # sample per world dropped — it carries the mesh's XLA compile)
    def world_median(world):
        times = step_times.get(world, [])
        times = times[1:] or times
        return stats.median(times) if times else 0.0

    worlds = sorted(step_times)
    ratio = {}
    if len(worlds) >= 2:
        small, full = worlds[0], worlds[-1]
        measured = world_median(small) / world_median(full) if world_median(full) else 0.0
        ratio = {
            "shrunk_world": small,
            "full_world": full,
            "measured": round(measured, 3),
            # the gang-telemetry prediction: fixed global batch, compute-
            # bound step ⇒ time scales with hosts_full / hosts_shrunk
            "predicted": round(full / small, 3),
        }
    return {
        "seed": seed,
        "ok": report["ok"],
        "phase": block.get("phase"),
        "passes": passes,
        "elapsed_s": round(elapsed, 3),
        "steps": trainers[-1].step if trainers else 0,
        "checkpoint_epochs": len(checkpoints),
        "pod_generations": len(trainers),
        "worker_pods_after": worker_pods_left,
        "fault_classes": sorted(schedule.fired),
        "faults_injected": faults,
        "resizes": [(r["kind"], r["from"], r["to"]) for r in block.get("shrinks") or []],
        "final_shape": block.get("shape"),
        "resume_latency_s": round(stats.median(resumes), 3) if resumes else 0.0,
        "lost_steps_total": lost,
        "lost_steps_per_fault": round(lost / faults, 3) if faults else 0.0,
        "max_lost_steps": report["max_lost_steps"],
        "rewinds": report["rewinds"],
        "continuity_violations": report["violations"],
        "shrink_step_time_ratio": ratio,
    }


def bench_serving(seed: int = 20260818) -> dict:
    """Traffic-driven elastic serving (ISSUE 14), both halves:

    1. the **decode bench** — the real continuous-batching engine vs the
       static-batch baseline over the same seeded arrival curve and the
       same int8/flash kernels (tokens/s/chip, occupancy, TTFT);
    2. the **control-plane drill** — a seeded diurnal sim driving a
       TPUServing through burst → scale-up (admitted through the
       placement engine), lull → fragmentation-aware scale-down, and a
       fabric-degraded replica excluded from routing.
    """
    from tpu_operator import consts
    from tpu_operator.api.tpuserving import new_tpu_serving
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.controllers.serving_controller import ServingReconciler
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.objects import new_object
    from tpu_operator.kube.sim import (
        DiurnalTraffic,
        PodKubelet,
        ServingTrafficSim,
        make_torus_nodes,
    )
    from tpu_operator.placement.engine import PlacementEngine
    from tpu_operator.workloads.serving import serving_decode_bench

    decode = serving_decode_bench(seed=seed)

    ns = "tpu-operator"
    slo_ttft = 5.0
    client = FakeClient()
    for node in make_torus_nodes((4, 2, 1), prefix="bench-sv"):
        node["metadata"]["labels"]["tpu.google.com/tpu.present"] = "true"
        client.create(node)
    client.create(new_tpu_serving("bench-serving", {
        "model": {"shape": "2x1x1"},
        "replicas": {"min": 1, "max": 3, "targetRps": 10.0,
                     "cooldownSeconds": 0.05},
        "slo": {"ttftP99Seconds": slo_ttft},
        "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 5},
    }))
    rec = ServingReconciler(client, ns)
    place = PlacementReconciler(client, ns)
    kubelet = PodKubelet(client, ns)
    sim = ServingTrafficSim(
        client, ns, "bench-serving", DiurnalTraffic(seed=seed), replica_rps=10.0,
        # window wide enough that the scale-up transient's queued
        # requests stay inside the p99 sample — the SLO check must
        # cover the event, not just the scaled steady state
        window=256,
    )
    req = Request(name="bench-serving")

    def block() -> dict:
        obj = client.get("tpu.google.com/v1alpha1", "TPUServing", "bench-serving")
        return (obj.get("status") or {}).get("serving") or {}

    def beat() -> None:
        rec.reconcile(req)
        place.reconcile(QUEUE_REQUEST)
        kubelet.step()  # the data plane rides along: one pod per replica
        sim.step()

    def fragmentation() -> float:
        plan = PlacementEngine(
            client.list("tpu.google.com/v1alpha1", "TPUSlice"),
            client.list("v1", "Node"),
        ).plan()
        return max(plan.fragmentation.values()) if plan.fragmentation else 0.0

    # -- steady low traffic: min replicas hold
    sim.override_rps = 3.0
    for _ in range(6):
        beat()
    steady = dict(block())

    # -- burst: immediate scale-up, admitted through the placement engine
    sim.override_rps = 20.0
    t0 = time.monotonic()
    burst_passes = 0
    for burst_passes in range(1, 40):
        beat()
        if block().get("ready") == 2:
            break
    scale_up_s = time.monotonic() - t0
    # ride the burst a few more beats so TTFT reflects the scaled fleet
    for _ in range(6):
        beat()
    burst = dict(block())
    worker_pods_at_burst = len(kubelet.serving_workers("bench-serving"))
    _, burst_ttft_p99 = sim.ttft_percentiles()

    # -- fabric degradation: the replica's own artifact excludes it
    replicas = sorted((burst.get("replicas") or {}))
    degraded_replica = replicas[0] if replicas else ""
    members = []
    if degraded_replica:
        obj = client.get("tpu.google.com/v1alpha1", "TPUSlice", degraded_replica)
        members = ((obj.get("status") or {}).get("placement") or {}).get("nodes") or []
        artifact = {
            "hosts": len(members), "members": members,
            "min_edge_gbps": 5.0, "median_edge_gbps": 100.0,
            "edges": {},
        }
        try:
            client.create(new_object(
                "v1", "ConfigMap", f"{degraded_replica}-gang", ns,
            ))
        except Exception:  # noqa: BLE001 — exists already
            pass
        client.patch(
            "v1", "ConfigMap", f"{degraded_replica}-gang",
            {"metadata": {"annotations": {
                consts.GANG_FABRIC_ANNOTATION: json.dumps(artifact),
            }}}, ns,
        )
    sim.routed = {}
    for _ in range(5):
        beat()
    excluded = dict(block())
    routed_during_exclusion = dict(sim.routed)
    # heal: drop the artifact so the lull runs on a clean fleet
    if degraded_replica:
        client.patch(
            "v1", "ConfigMap", f"{degraded_replica}-gang",
            {"metadata": {"annotations": {consts.GANG_FABRIC_ANNOTATION: None}}},
            ns,
        )

    # -- lull: hysteretic scale-down, fragmentation-aware victims
    frag_before_scale_down = fragmentation()
    sim.override_rps = 3.0
    for _ in range(30):
        beat()
        time.sleep(0.01)
        if block().get("desired") == 1 and block().get("ready") == 1:
            break
    lull = dict(block())
    frag_after_scale_down = fragmentation()

    # -- deletion: series retired, owned replicas AND worker pods swept
    client.delete("tpu.google.com/v1alpha1", "TPUServing", "bench-serving")
    rec.reconcile(req)
    kubelet.step()  # retire the swept pods' mains
    slices_left = [
        s["metadata"]["name"]
        for s in client.list("tpu.google.com/v1alpha1", "TPUSlice")
    ]
    worker_pods_after_delete = len(kubelet.serving_workers("bench-serving"))
    kubelet.stop()

    return {
        "seed": seed,
        "decode": decode,
        "tokens_per_s_chip_continuous": decode["continuous"]["tokens_per_s_chip"],
        "tokens_per_s_chip_static": decode["static"]["tokens_per_s_chip"],
        "continuous_vs_static_speedup": decode["continuous_vs_static_speedup"],
        "decode_ttft_p50_s": decode["continuous"]["ttft_p50_s"],
        "decode_ttft_p99_s": decode["continuous"]["ttft_p99_s"],
        "sim": {
            "steady": {"phase": steady.get("phase"), "ready": steady.get("ready")},
            "burst": {
                "phase": burst.get("phase"), "ready": burst.get("ready"),
                "desired": burst.get("desired"),
            },
            "scale_up_passes": burst_passes,
            "scale_up_time_to_ready_s": round(scale_up_s, 3),
            "slo_ttft_p99_s": slo_ttft,
            "burst_ttft_p99_s": round(burst_ttft_p99, 3),
            "worker_pods_at_burst": worker_pods_at_burst,
            "worker_pods_after_delete": worker_pods_after_delete,
            "degraded_replica": degraded_replica,
            "degraded_replica_members": members,
            "routed_during_exclusion": routed_during_exclusion,
            "excluded_phase": excluded.get("phase"),
            "lull": {
                "phase": lull.get("phase"), "ready": lull.get("ready"),
                "desired": lull.get("desired"),
            },
            "decisions": lull.get("decisions"),
            "fragmentation_before_scale_down": frag_before_scale_down,
            "fragmentation_after_scale_down": frag_after_scale_down,
            "slices_after_delete": slices_left,
        },
    }


def serving_smoke() -> int:
    """CI gate (scripts/ci.sh): the serving acceptance run, with the
    pod data plane riding along — continuous batching must beat the
    static baseline by >= 1.5x tokens/s/chip on the same kernels, the
    autoscaler must ride the seeded diurnal sim (burst -> scale-up
    admitted through placement with p99 TTFT inside the SLO, one
    sim-kubelet worker pod per ready replica, lull ->
    fragmentation-aware scale-down), a fabric-degraded replica must
    receive zero routed requests, and every serving series must be live
    on the scrape endpoint while the CR exists and retired when it is
    deleted (worker pods swept with it)."""
    import prometheus_client

    result = bench_serving()
    sim = result["sim"]
    serving_series = (
        "tpu_operator_serving_replicas",
        "tpu_operator_serving_tokens_per_s",
        "tpu_operator_serving_ttft_p99_seconds",
        "tpu_operator_serving_queue_depth",
    )
    # bench_serving ends with the CR deleted: series must be retired NOW,
    # and must have been live while it served (gauges still registered)
    scrape = prometheus_client.generate_latest(prometheus_client.REGISTRY).decode()
    series_registered = all(name in scrape for name in serving_series)
    series_retired = all(
        f'{name}{{serving="bench-serving"}}' not in scrape for name in serving_series
    )
    degraded = sim["degraded_replica"]
    routed = sim["routed_during_exclusion"]
    checks = {
        "continuous_1_5x_over_static": result["continuous_vs_static_speedup"] >= 1.5,
        "decode_ttft_improves": (
            result["decode"]["continuous"]["ttft_p99_s"]
            < result["decode"]["static"]["ttft_p99_s"]
        ),
        "steady_holds_min": sim["steady"]["ready"] == 1,
        "burst_scales_up": sim["burst"]["ready"] >= 2 and sim["burst"]["desired"] >= 2,
        # the pod data plane: one worker pod per ready replica at the
        # burst, all of them swept with the CR
        "worker_pods_ride_replicas": (
            sim["worker_pods_at_burst"] == sim["burst"]["ready"]
        ),
        "delete_sweeps_worker_pods": sim["worker_pods_after_delete"] == 0,
        "ttft_within_slo_across_scale_up": (
            0 < sim["burst_ttft_p99_s"] <= sim["slo_ttft_p99_s"]
        ),
        "degraded_fabric_zero_routed": (
            bool(degraded) and routed.get(degraded, 0) == 0
            and sum(routed.values()) > 0
        ),
        "excluded_reads_degraded": sim["excluded_phase"] == "Degraded",
        "lull_scales_down": sim["lull"]["ready"] == 1 and sim["lull"]["desired"] == 1,
        "scale_down_non_increasing_fragmentation": (
            sim["fragmentation_after_scale_down"]
            <= sim["fragmentation_before_scale_down"]
        ),
        "victim_decisions_recorded": any(
            d.get("action") == "victim" for d in sim["decisions"] or []
        ),
        "delete_sweeps_replicas": sim["slices_after_delete"] == [],
        "series_live_then_retired": series_registered and series_retired,
    }
    ok = all(checks.values())
    print(json.dumps({
        "metric": "serving_smoke",
        "ok": ok,
        "checks": checks,
        "tokens_per_s_chip_continuous": result["tokens_per_s_chip_continuous"],
        "tokens_per_s_chip_static": result["tokens_per_s_chip_static"],
        "continuous_vs_static_speedup": result["continuous_vs_static_speedup"],
        "scale_up_time_to_ready_s": sim["scale_up_time_to_ready_s"],
        "burst_ttft_p99_s": sim["burst_ttft_p99_s"],
        "fragmentation_before_after": [
            sim["fragmentation_before_scale_down"],
            sim["fragmentation_after_scale_down"],
        ],
    }, separators=(",", ":")))
    return 0 if ok else 1


def bench_pods(seed: int = 20260806) -> dict:
    """The pod data plane end to end (ISSUE 16): worker pods under the
    sim kubelet, the KV-aware router, and disaggregated prefill/decode
    pools.

    1. **KV affinity** — warm multi-turn sessions (router session
       affinity + engine session-KV retention: follow-up turns
       delta-prefill from the held context) vs cold single-shot prompts
       of the SAME lengths, paced by the same seeded
       :class:`DiurnalTraffic` arrivals (equal load): warm TTFT must
       beat cold TTFT.
    2. **disaggregation** — ``spec.disaggregation`` splits the serving
       into a prefill pool scaled on ITS signal (prefill TTFT p99 vs
       the SLO) and a decode pool scaled on ITS signal (tokens/s
       floor), bridged by paged-KV handoffs the router collects.
    """
    from tpu_operator import consts
    from tpu_operator.api.tpuserving import new_tpu_serving
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.controllers.serving_controller import ServingReconciler
    from tpu_operator.dataplane.router import KVAwareRouter
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.sim import DiurnalTraffic, PodKubelet, make_torus_nodes
    from tpu_operator.workloads.serving import ServingRequest

    import numpy as np

    ns = "tpu-operator"
    rng = np.random.default_rng(seed)

    def ttft_p50(requests: list) -> float:
        ttfts = sorted(r.ttft_s for r in requests if r.ttft_s is not None)
        if not ttfts:
            return 0.0
        return ttfts[len(ttfts) // 2]

    # ---- part 1: session affinity (aggregated serving, 2 replicas) ---------
    client = FakeClient()
    for node in make_torus_nodes((4, 2, 1), prefix="bench-pd"):
        node["metadata"]["labels"]["tpu.google.com/tpu.present"] = "true"
        client.create(node)
    client.create(new_tpu_serving("bench-pods", {
        "model": {"shape": "2x1x1"},
        "replicas": {"min": 2, "max": 2, "targetRps": 100.0},
        "slo": {"ttftP99Seconds": 30.0},
        "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 5},
    }))
    rec = ServingReconciler(client, ns)
    place = PlacementReconciler(client, ns)
    kubelet = PodKubelet(client, ns)
    req = Request(name="bench-pods")
    for _ in range(10):
        rec.reconcile(req)
        place.reconcile(QUEUE_REQUEST)
        kubelet.step()
        if len(kubelet.serving_workers("bench-pods")) == 2:
            break
    worker_pods = len(kubelet.serving_workers("bench-pods"))
    router = KVAwareRouter(client, ns, "bench-pods")
    traffic = DiurnalTraffic(seed=seed)

    # 4 warm conversations x 3 turns; every turn is mirrored by a cold
    # single-shot request of the SAME prompt length submitted in the
    # same tick — equal load, the only delta is the session tag
    sessions = 4
    turn_plens = [16, 28, 40]
    decode = 4

    def warm_prompt(j: int, turn: int) -> np.ndarray:
        # one growing conversation per session: turn k's prompt extends
        # turn k-1's context (prompt + its decoded tokens)
        r = np.random.default_rng(seed + 100 + j)
        return r.integers(0, 128, size=turn_plens[turn]).astype(np.int32)

    tick = 0
    completed_rids: set = set()
    for turn in range(len(turn_plens)):
        pairs = []
        for j in range(sessions):
            pairs.append((
                ServingRequest(
                    rid=f"warm-{j}-t{turn}", prompt=warm_prompt(j, turn),
                    decode_tokens=decode, session=f"conv-{j}",
                ),
                ServingRequest(
                    rid=f"cold-{j}-t{turn}",
                    prompt=rng.integers(0, 128, size=turn_plens[turn]).astype(np.int32),
                    decode_tokens=decode,
                ),
            ))
        # pace this turn's pairs by the seeded arrival curve, then drain
        # the round fully: a session's next turn resumes its RETAINED
        # context, so turns never overlap in flight
        for _ in range(400):
            if pairs:
                for _n in range(max(1, traffic.arrivals(tick))):
                    if not pairs:
                        break
                    warm, cold = pairs.pop(0)
                    router.submit(warm)
                    router.submit(cold)
            router.sync_workers(kubelet.mains())
            router.tick()
            kubelet.step()
            tick += 1
            done = {r.rid for r in router.completed_requests()}
            if not pairs and all(
                f"warm-{j}-t{turn}" in done and f"cold-{j}-t{turn}" in done
                for j in range(sessions)
            ):
                break
    finished = router.completed_requests()
    # turn 0 is every conversation's cold start — the affinity win is
    # turns >= 1, where the warm side delta-prefills the held context
    warm_done = [r for r in finished
                 if r.rid.startswith("warm-") and not r.rid.endswith("-t0")]
    cold_done = [r for r in finished
                 if r.rid.startswith("cold-") and not r.rid.endswith("-t0")]
    affinity = {
        "worker_pods": worker_pods,
        "warm_requests": len(warm_done),
        "cold_requests": len(cold_done),
        "warm_ttft_p50_s": round(ttft_p50(warm_done), 5),
        "cold_ttft_p50_s": round(ttft_p50(cold_done), 5),
        "kv_hit_ratio": round(router.kv_hit_ratio, 4),
        "prefix_routed": router.prefix_routed,
        "routed": dict(router.routed),
    }
    client.delete("tpu.google.com/v1alpha1", "TPUServing", "bench-pods")
    rec.reconcile(req)
    kubelet.step()
    affinity["worker_pods_after_delete"] = len(
        kubelet.serving_workers("bench-pods"))
    kubelet.stop()

    # ---- part 2: disaggregated prefill/decode pools ------------------------
    client2 = FakeClient()
    for node in make_torus_nodes((4, 2, 1), prefix="bench-dg"):
        node["metadata"]["labels"]["tpu.google.com/tpu.present"] = "true"
        client2.create(node)
    client2.create(new_tpu_serving("bench-disagg", {
        "model": {"shape": "1x1x1"},
        # targetRps far above offered load: any decode scale-up is the
        # floor signal's, not the arrival-rate autoscaler's
        "replicas": {"min": 1, "max": 3, "targetRps": 1000.0,
                     "cooldownSeconds": 0.0},
        # any real prefill breaches 10 ms: the prefill pool must scale
        # on ITS OWN signal while decode holds
        "slo": {"ttftP99Seconds": 0.01},
        "disaggregation": {"enabled": True, "prefillMin": 1, "prefillMax": 2,
                           "decodeTokensPerSFloor": 1e9},
        "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 5},
    }))
    rec2 = ServingReconciler(client2, ns)
    place2 = PlacementReconciler(client2, ns)
    kubelet2 = PodKubelet(client2, ns)
    router2 = KVAwareRouter(client2, ns, "bench-disagg")
    req2 = Request(name="bench-disagg")

    def disagg_block() -> dict:
        obj2 = client2.get(
            "tpu.google.com/v1alpha1", "TPUServing", "bench-disagg")
        return (obj2.get("status") or {}).get("serving") or {}

    rid = 0
    for _ in range(80):
        rec2.reconcile(req2)
        place2.reconcile(QUEUE_REQUEST)
        kubelet2.step()
        router2.sync_workers(kubelet2.mains())
        if router2.prefill_workers:
            for _ in range(2):
                router2.submit(ServingRequest(
                    rid=f"dg-{rid}",
                    prompt=rng.integers(0, 128, size=24).astype(np.int32),
                    decode_tokens=4,
                    session=f"dg-conv-{rid % 3}",
                ))
                rid += 1
        router2.tick()
        b = disagg_block()
        pools_now = b.get("pools") or {}
        if (
            (pools_now.get("prefill") or {}).get("desired", 0) >= 2
            and (pools_now.get("decode") or {}).get("desired", 0) >= 2
            and router2.handoffs > 0
            and router2.completed_requests()
        ):
            break
    # drain what's still in flight so "completed" reflects the pools
    for _ in range(40):
        if not (router2.queue or any(
                not m.engine.idle for m in list(router2.workers.values())
                + list(router2.prefill_workers.values()))):
            break
        kubelet2.step()
        router2.sync_workers(kubelet2.mains())
        router2.tick()
    block2 = disagg_block()
    pools = block2.get("pools") or {}
    decisions = block2.get("decisions") or []
    disagg = {
        "pools": pools,
        "prefill_desired": (pools.get("prefill") or {}).get("desired", 0),
        "prefill_ready": (pools.get("prefill") or {}).get("ready", 0),
        "decode_desired": (pools.get("decode") or {}).get("desired", 0),
        "decode_ready": (pools.get("decode") or {}).get("ready", 0),
        "handoffs": router2.handoffs,
        "handoff_bytes": router2.handoff_bytes,
        "completed": len(router2.completed_requests()),
        "submitted": rid,
        "prefill_scale_decisions": [
            d.get("reason") for d in decisions
            if d.get("action") == "prefill-scale"
        ],
        "decode_floor_decisions": [
            d.get("reason") for d in decisions
            if "decode throughput" in (d.get("reason") or "")
        ],
    }
    client2.delete("tpu.google.com/v1alpha1", "TPUServing", "bench-disagg")
    rec2.reconcile(req2)
    kubelet2.step()
    disagg["worker_pods_after_delete"] = len(
        kubelet2.serving_workers("bench-disagg"))
    kubelet2.stop()

    return {"seed": seed, "affinity": affinity, "disagg": disagg}


def pod_smoke() -> int:
    """CI gate (scripts/ci.sh): the pod data plane acceptance run —
    worker pods under the sim kubelet with the KV-aware router must
    show the session-affinity win (warm-session TTFT strictly below
    cold-session TTFT at equal load on the seeded DiurnalTraffic), the
    disaggregated pools must each scale on their OWN signal (prefill on
    prefill TTFT p99, decode on the tokens/s floor) with paged-KV
    handoffs flowing between them, and deleting the CRs must sweep
    every worker pod. ci.sh runs the gate twice — plain and
    TPUOP_RACECHECK=1 (failed by racecheck.violations())."""
    result = bench_pods()
    aff, dg = result["affinity"], result["disagg"]
    checks = {
        "workers_attached": aff["worker_pods"] == 2,
        "equal_load": (
            aff["warm_requests"] == aff["cold_requests"]
            and aff["warm_requests"] > 0
        ),
        "warm_ttft_beats_cold": (
            0 < aff["warm_ttft_p50_s"] < aff["cold_ttft_p50_s"]
        ),
        "session_affinity_hits": aff["kv_hit_ratio"] >= 0.5,
        "affinity_delete_sweeps_pods": aff["worker_pods_after_delete"] == 0,
        "prefill_pool_scaled_on_ttft": (
            dg["prefill_desired"] >= 2 and bool(dg["prefill_scale_decisions"])
        ),
        "decode_pool_scaled_on_floor": (
            dg["decode_desired"] >= 2 and bool(dg["decode_floor_decisions"])
        ),
        "kv_handoff_flowed": dg["handoffs"] > 0 and dg["handoff_bytes"] > 0,
        "requests_completed_through_pools": dg["completed"] > 0,
        "disagg_delete_sweeps_pods": dg["worker_pods_after_delete"] == 0,
    }
    violations = []
    if os.environ.get("TPUOP_RACECHECK") == "1":
        from tpu_operator.kube import racecheck

        violations = [repr(v) for v in racecheck.violations()]
    checks["racecheck_clean"] = not violations
    ok = all(checks.values())
    print(json.dumps({
        "metric": "pod_smoke",
        "ok": ok,
        "checks": checks,
        "affinity": aff,
        "disagg": {k: v for k, v in dg.items() if k != "pools"},
        "racecheck_violations": violations,
    }, separators=(",", ":")))
    return 0 if ok else 1


def job_smoke() -> int:
    """CI gate (scripts/ci.sh): the chaos acceptance run for elastic
    training, end to end through sim-kubelet worker pods — a seeded
    schedule mixing host death, grey failure, link cut and preemption
    against a placed TPUJob must end Succeeded with contiguous epoch
    history across pod generations (no step lost beyond the last
    checkpoint), shrinking only to allocator-ranked blocks and growing
    back on heal, sweeping the gang's pods on success; and a job with
    an unplaceable min shape must land Failed with an Event instead of
    crash-looping through the placement queue."""
    from tpu_operator.api.tpujob import JobPhase, new_tpu_job
    from tpu_operator.controllers.job_controller import JobReconciler
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.sim import GangFaultSchedule, make_torus_nodes

    result = bench_training()
    checks = {
        "succeeded": result["phase"] == "Succeeded",
        "continuity_ok": result["ok"],
        # the pod data plane: every re-place rolled a new worker-pod
        # generation (the faults guarantee at least one), and success
        # swept the gang's worker pods
        "pod_generations_rolled": result["pod_generations"] >= 2,
        "workers_swept_on_success": result["worker_pods_after"] == [],
        "all_fault_classes_fired": (
            set(result["fault_classes"]) == set(GangFaultSchedule.FAULT_CLASSES)
        ),
        # the resume guarantee: lost work bounded by the cadence
        "lost_bounded_by_cadence": result["max_lost_steps"] <= 5,
        # shrinks landed only on allocator-ranked sub-blocks
        "shapes_allocator_ranked": all(
            to in ("2x2x1", "2x1x1", "1x1x1") for _, _, to in result["resizes"]
        ),
        "shrank_and_grew": (
            any(k == "shrink" for k, _, _ in result["resizes"])
            and any(k == "grow" for k, _, _ in result["resizes"])
        ),
        "grew_back_to_desired": result["final_shape"] == "2x2x1",
        # both worlds produced a measurable step-time series (the ratio
        # itself is only gated against the hosts-ratio prediction on a
        # real accelerator: the CPU sim multiplexes every virtual device
        # onto one host, so a shrunk mesh is NOT compute-bound slower)
        "shrink_ratio_measured": (
            bool(result["shrink_step_time_ratio"])
            and result["shrink_step_time_ratio"]["measured"] > 0.0
        ),
        "shrink_ratio_within_prediction_on_tpu": bool(
            os.environ.get("BENCH_SKIP_DEVICE")
            or not result["shrink_step_time_ratio"]
            or 0.8 <= result["shrink_step_time_ratio"]["measured"]
            <= 4.0 * result["shrink_step_time_ratio"]["predicted"]
        ),
    }
    # the quarantine half: an unplaceable min shape must Fail with an
    # Event after the budget, not crash-loop
    ns = "tpu-operator"
    client = FakeClient()
    for node in make_torus_nodes((2, 2, 1), prefix="smoke-q"):
        client.create(node)
    client.create(new_tpu_job("toobig", {
        "workload": {"steps": 10},
        "gang": {"shape": "4x4x4", "minShape": "4x4x1"},
        "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 2},
    }))
    job_rec = JobReconciler(client, ns)
    place_rec = PlacementReconciler(client, ns)
    for _ in range(8):
        job_rec.reconcile(Request(name="toobig"))
        place_rec.reconcile(QUEUE_REQUEST)
    job = client.get("tpu.google.com/v1alpha1", "TPUJob", "toobig")
    block = (job.get("status") or {}).get("job") or {}
    checks["unplaceable_min_quarantines"] = block.get("phase") == JobPhase.FAILED
    checks["quarantine_evented"] = any(
        e.get("reason") == "JobFailed" for e in client.list("v1", "Event", "default")
    )
    checks["quarantine_frees_queue_slot"] = (
        client.get_or_none("tpu.google.com/v1alpha1", "TPUSlice", "toobig-slice") is None
    )
    ok = all(checks.values())
    print(json.dumps({
        "metric": "job_smoke",
        "ok": ok,
        "checks": checks,
        **{k: v for k, v in result.items() if k != "continuity_violations"},
        **({"continuity_violations": result["continuity_violations"]}
           if result["continuity_violations"] else {}),
    }, separators=(",", ":")))
    return 0 if ok else 1


def defrag_smoke() -> int:
    """CI gate (scripts/ci.sh): scheduled defragmentation end to end on
    the seeded fragmented 512-host torus —

    1. a mixed churn leaves the torus fragmented enough that a 4x4x4
       gang is Unschedulable;
    2. while a PLACEABLE slice is queued (placement in flight) the
       defrag controller proposes ZERO migrations;
    3. once idle, defrag migrates (serving replicas via the
       drain-then-re-place path), the 4x4x4 lands, and the realized
       fragmentation strictly decreases (`DefragMigrated` evidence);
    4. the TPUJob checkpoint-barrier path moves a Running job's gang
       with its step watermark intact (defragRequest → `defrag-` token
       → checkpoint ack → teardown → re-place → Resuming);
    5. the fleet simulator's defrag-aware policy beats best-fit on p99
       time-to-place AND ends with strictly lower fragmentation under
       the seeded churn schedule.

    ci.sh runs the whole gate twice — plain and TPUOP_RACECHECK=1 (the
    instrumented-locks leg, failed by racecheck.violations())."""
    import random as random_mod

    from tpu_operator import consts
    from tpu_operator.api.tpujob import JobPhase, new_tpu_job
    from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, new_tpu_slice
    from tpu_operator.controllers.defrag_controller import (
        DEFRAG_REQUEST,
        DefragReconciler,
    )
    from tpu_operator.controllers.job_controller import JobReconciler
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.objects import new_object
    from tpu_operator.kube.sim import GangChurnSchedule, make_torus_nodes
    from tpu_operator.planning.sim import FleetSimulator

    ns = "tpu-operator"
    checks: dict = {}

    def build_fragmented(prefix: str):
        """The seeded fragmented 512-host torus: 32 serving-owned pair
        gangs placed, half deleted (seed pinned: same churn, same
        holes). Returns (client, placement reconciler)."""
        client = FakeClient()
        for node in make_torus_nodes((8, 8, 8), prefix=prefix):
            client.create(node)
        rng = random_mod.Random(0)
        place = PlacementReconciler(client, ns)
        shapes = ["2x2x2", "4x2x2", "4x4x2", "2x2x1"]
        names = []
        for i in range(32):
            body = new_tpu_slice(
                f"g{i}", {"placement": {"shape": rng.choice(shapes)}}
            )
            body["metadata"]["ownerReferences"] = [{
                "apiVersion": "tpu.google.com/v1alpha1", "kind": "TPUServing",
                "name": f"svc{i // 2}", "uid": f"u{i // 2}",
            }]
            client.create(body)
            names.append(f"g{i}")
        place.reconcile(QUEUE_REQUEST)
        for name in rng.sample(names, 16):
            client.delete(TPU_SLICE_API_VERSION, "TPUSlice", name)
        place.reconcile(QUEUE_REQUEST)
        place.reconcile(QUEUE_REQUEST)
        return client, place

    def phase_on(client, name: str) -> str:
        obj = client.get_or_none(TPU_SLICE_API_VERSION, "TPUSlice", name)
        return (((obj or {}).get("status") or {}).get("placement") or {}).get(
            "phase", ""
        )

    def decisions_on(client) -> list:
        cm = client.get_or_none(
            "v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP, ns
        )
        raw = ((cm or {}).get("data") or {}).get(consts.DEFRAG_STATE_KEY, "")
        try:
            return (json.loads(raw) or {}).get("decisions", [])
        except ValueError:
            return []

    def controller_on(client):
        defrag = DefragReconciler(client, ns)
        clock = [1000.0]
        defrag._now = lambda: clock[0]
        return defrag, clock

    # -- part 1: pure consolidation — with NO pending demand, a defrag
    # migration must strictly reduce the pool's measured fragmentation
    # (predicted delta must match realized)
    client_a, place_a = build_fragmented("da")
    defrag_a, clock_a = controller_on(client_a)
    defrag_a.reconcile(DEFRAG_REQUEST)   # proposes + executes
    place_a.reconcile(QUEUE_REQUEST)     # re-places the drained gang
    defrag_a.reconcile(DEFRAG_REQUEST)   # settles realized frag
    settled_a = [d for d in decisions_on(client_a) if d.get("realized_frag") is not None]
    checks["pure_defrag_reduces_fragmentation"] = bool(settled_a) and all(
        d["realized_frag"] < d["frag_before"] for d in settled_a
    )
    checks["predicted_matches_realized"] = bool(settled_a) and all(
        abs(d["realized_frag"] - d["predicted_frag"]) < 1e-9 for d in settled_a
    )
    frag_before = settled_a[0]["frag_before"] if settled_a else None

    # -- parts 2+3: the rescue scenario — a 4x4x4 is Unschedulable; zero
    # migrations while a PLACEABLE slice is queued; then defrag reclaims
    # the capacity and the 4x4x4 lands (defrag-off stays stuck)
    client, place = build_fragmented("df")
    client.create(new_tpu_slice("wanted", {"placement": {"shape": "4x4x4"}}))
    place.reconcile(QUEUE_REQUEST)
    checks["wanted_unplaceable_before_defrag"] = (
        phase_on(client, "wanted") == "Unschedulable"
    )
    defrag, clock = controller_on(client)

    client.create(new_tpu_slice("queued-probe", {"placement": {"shape": "2x2x1"}}))
    defrag.reconcile(DEFRAG_REQUEST)  # probe is un-placed: placement in flight
    checks["zero_migrations_while_queued"] = not any(
        d.get("executed_at") is not None for d in decisions_on(client)
    )
    place.reconcile(QUEUE_REQUEST)  # seat the probe
    # probe done: free its block so the fragmented scenario is untouched
    client.delete(TPU_SLICE_API_VERSION, "TPUSlice", "queued-probe")
    place.reconcile(QUEUE_REQUEST)  # back to idle

    for _ in range(3):
        place.reconcile(QUEUE_REQUEST)
    checks["defrag_off_stays_unschedulable"] = (
        phase_on(client, "wanted") == "Unschedulable"
    )
    landed = False
    for round_no in range(6):
        clock[0] += consts.DEFRAG_COOLDOWN_SECONDS + 1.0
        defrag.reconcile(DEFRAG_REQUEST)
        place.reconcile(QUEUE_REQUEST)
        defrag.reconcile(DEFRAG_REQUEST)  # settle pass books realized frag
        if phase_on(client, "wanted") == "Scheduled":
            landed = True
            break
    decisions = decisions_on(client)
    checks["wanted_lands_after_defrag"] = landed
    checks["migrations_executed"] = any(
        d.get("executed_at") is not None for d in decisions
    )
    # the rescue decision explicitly reclaimed capacity for the parked
    # gang (the seated 64-host block raises the residual-free-space
    # fragmentation number — reclaimed capacity, not regression; the
    # strict-decrease gate is part 1's, where no pending gang lands)
    checks["rescue_decision_seats_wanted"] = any(
        "wanted" in (d.get("lands_pending") or []) for d in decisions
    )
    events = [e.get("reason") for e in client.list("v1", "Event", "default")]
    checks["defrag_migrated_evented"] = "DefragMigrated" in events

    # -- part 4: the TPUJob checkpoint-barrier migration path ----------------
    jc = FakeClient()
    for node in make_torus_nodes((4, 2, 1), prefix="jb"):
        jc.create(node)
    jc.create(new_tpu_job("tj", {
        "workload": {"steps": 1000},
        "gang": {"shape": "2x2x1", "minShape": "2x2x1"},
    }))
    job_rec = JobReconciler(jc, ns)
    place_j = PlacementReconciler(jc, ns)
    progress_name = "tj" + consts.JOB_PROGRESS_SUFFIX

    def fake_trainer() -> None:
        """The scripted gang side: publish running progress and echo any
        checkpoint barrier token (the InProcessJobRunner contract,
        compressed to what the barrier needs)."""
        cm = jc.get_or_none("v1", "ConfigMap", progress_name, ns)
        if cm is None:
            jc.create(new_object("v1", "ConfigMap", progress_name, ns, data={}))
            cm = jc.get("v1", "ConfigMap", progress_name, ns)
        slice_obj = jc.get_or_none(TPU_SLICE_API_VERSION, "TPUSlice", "tj-slice")
        placement = ((slice_obj or {}).get("status") or {}).get("placement") or {}
        hosts = len(placement.get("nodes") or [])
        data = {
            consts.JOB_PROGRESS_STEP: "42",
            consts.JOB_PROGRESS_CHECKPOINT_STEP: "40",
            consts.JOB_PROGRESS_EPOCH: "4",
            consts.JOB_PROGRESS_WORLD: str(hosts),
            consts.JOB_PROGRESS_STATUS: consts.JOB_PROGRESS_RUNNING,
        }
        request = (cm.get("data") or {}).get(consts.JOB_CHECKPOINT_REQUEST, "")
        if request:
            data[consts.JOB_PROGRESS_CHECKPOINT_ACK] = request
        jc.patch("v1", "ConfigMap", progress_name, {"data": data}, ns)

    for _ in range(4):
        job_rec.reconcile(Request(name="tj"))
        place_j.reconcile(QUEUE_REQUEST)
        fake_trainer()
    job = jc.get("tpu.google.com/v1alpha1", "TPUJob", "tj")
    block = (job.get("status") or {}).get("job") or {}
    checks["job_running_before_migration"] = block.get("phase") == JobPhase.RUNNING
    source_nodes = set()
    for n in jc.list("v1", "Node"):
        if (n["metadata"].get("labels") or {}).get(consts.PLACEMENT_LABEL) == "tj-slice":
            source_nodes.add(n["metadata"]["name"])
    # the defrag controller's execution primitive: its one owned key
    jc.patch(
        "v1", "ConfigMap", progress_name,
        {"data": {consts.JOB_DEFRAG_REQUEST: "defrag-smoke-1"}}, ns,
    )
    phases_seen = []
    for _ in range(8):
        job_rec.reconcile(Request(name="tj"))
        job = jc.get("tpu.google.com/v1alpha1", "TPUJob", "tj")
        phases_seen.append(((job.get("status") or {}).get("job") or {}).get("phase"))
        place_j.reconcile(QUEUE_REQUEST)
        fake_trainer()
    block = (job.get("status") or {}).get("job") or {}
    checks["job_checkpointed_before_move"] = JobPhase.CHECKPOINTING in phases_seen
    checks["job_back_running_after_move"] = block.get("phase") == JobPhase.RUNNING
    checks["job_step_watermark_intact"] = block.get("step") == 42
    checks["job_defrag_token_honored"] = block.get("defragHandled") == "defrag-smoke-1"
    job_events = [e.get("reason") for e in jc.list("v1", "Event", "default")]
    checks["job_migrating_evented"] = "JobMigrating" in job_events
    # idempotency: the same token never migrates twice
    barriers_before = block.get("barrierSeq")
    for _ in range(3):
        job_rec.reconcile(Request(name="tj"))
        fake_trainer()
    job = jc.get("tpu.google.com/v1alpha1", "TPUJob", "tj")
    block = (job.get("status") or {}).get("job") or {}
    checks["job_stale_token_ignored"] = block.get("barrierSeq") == barriers_before

    # -- part 5: fleet sim — defrag-aware beats best-fit ---------------------
    def schedule():
        return GangChurnSchedule(
            seed=11, ticks=140, arrivals_per_tick=1.1,
            shapes=(
                ((2, 2, 1), 4.0), ((2, 2, 2), 3.0), ((4, 2, 2), 2.0),
                ((4, 4, 2), 1.0), ((4, 4, 4), 0.6),
            ),
            min_lifetime=25, max_lifetime=70,
        )

    reports = {}
    for policy in ("best-fit", "defrag-aware"):
        sim = FleetSimulator(
            dims=(8, 8, 8), policy=policy,
            migration_cooldown_ticks=6, defrag_every=3,
        )
        reports[policy] = sim.run(schedule(), drain_ticks=30)
    checks["sim_defrag_beats_best_fit_p99"] = (
        reports["defrag-aware"]["time_to_place_p99_s"]
        < reports["best-fit"]["time_to_place_p99_s"]
    )
    checks["sim_defrag_lower_end_fragmentation"] = (
        reports["defrag-aware"]["fragmentation"]
        < reports["best-fit"]["fragmentation"]
    )
    checks["sim_migrations_happened"] = reports["defrag-aware"]["migrations"] >= 1

    violations = []
    if os.environ.get("TPUOP_RACECHECK") == "1":
        from tpu_operator.kube import racecheck

        violations = [repr(v) for v in racecheck.violations()]
    checks["racecheck_clean"] = not violations
    ok = all(checks.values())
    print(json.dumps({
        "metric": "defrag_smoke",
        "ok": ok,
        "checks": checks,
        "frag_before": frag_before,
        "decisions": decisions[-3:],
        "fleet_sim": {
            p: {k: r[k] for k in (
                "utilization_pct", "time_to_place_p50_s", "time_to_place_p99_s",
                "migrations", "fragmentation",
            )} for p, r in reports.items()
        },
        "racecheck_violations": violations,
    }, separators=(",", ":")))
    return 0 if ok else 1


def _predict_training_run(predictive: bool, seed: int = 20260807) -> dict:
    """One seeded host-death-with-precursors run of a real TPUJob (pod
    data plane, real trainers), with the risk scorer either driven
    (``predictive=True``) or absent. Same seed → same schedule → the
    SAME pre-chosen victim and kill pass either way, so the pair
    isolates exactly what prediction buys: the planned checkpoint-
    barrier migration walks the gang off the dying host for zero lost
    steps, while the reactive run rewinds to the last cadence
    checkpoint."""
    import tempfile

    from tpu_operator import consts
    from tpu_operator.api.tpujob import JobPhase, new_tpu_job
    from tpu_operator.controllers.job_controller import JobReconciler
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.controllers.risk import RiskScorer
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.sim import GangFaultSchedule, PodKubelet, make_torus_nodes
    from tpu_operator.workloads.training import verify_continuity

    ns = "tpu-operator"
    client = FakeClient()
    for node in make_torus_nodes((2, 2, 2), prefix="bench-pr"):
        node["metadata"]["labels"]["tpu.google.com/tpu.present"] = "true"
        client.create(node)
    store_dir = tempfile.mkdtemp(prefix="bench-predict-")
    client.create(new_tpu_job("pred-job", {
        "workload": {"steps": 120},
        "gang": {"shape": "2x2x1", "minShape": "1x1x1"},
        "checkpoint": {"everySteps": 10, "dir": store_dir},
        "backoff": {"baseSeconds": 0.01, "maxSeconds": 0.05, "retryLimit": 10},
    }))
    job_rec = JobReconciler(client, ns)
    place_rec = PlacementReconciler(client, ns)
    kubelet = PodKubelet(client, ns)
    # kill at pass 16, precursors (rising straggler ratio naming the
    # pre-chosen victim) over passes 8..15 — window enough for score to
    # cross threshold AND the barrier round-trip to land before the kill
    schedule = GangFaultSchedule(
        client, ns, "pred-job-slice", seed=seed, classes=("host-death",),
        start_at=16, every=10, heal_after=4, precursor_passes=8,
    )
    risk = RiskScorer(client, ns)
    clock = [0.0]
    risk._now = lambda: clock[0]
    phases_seen = set()
    passes = 0
    block: dict = {}
    for passes in range(1, 400):
        job_rec.reconcile(Request(name="pred-job"))
        place_rec.reconcile(QUEUE_REQUEST)
        kubelet.step()
        schedule.step()
        if predictive:
            # 10 s/pass: the kill lands ~7 passes after the planned
            # migration, INSIDE the settle grace window, so the
            # prediction books realized=true instead of false-alarming
            clock[0] += 10.0
            risk.sync()
        job = client.get("tpu.google.com/v1alpha1", "TPUJob", "pred-job")
        block = (job.get("status") or {}).get("job") or {}
        phases_seen.add(block.get("phase"))
        if block.get("phase") == JobPhase.SUCCEEDED:
            break
    trainers = kubelet.job_trainers("pred-job")
    kubelet.stop()
    history = [h for t in trainers for h in t.history]
    checkpoints = [c for t in trainers for c in t.checkpoints]
    total_steps = trainers[-1].total_steps if trainers else 120
    report = verify_continuity(history, checkpoints, total_steps)
    executed = [h["step"] for h in history]
    victim = next(
        (r[3] for r in schedule.log if r[1] == "inject" and r[2] == "host-death"),
        "",
    )
    migrations = []
    if predictive:
        from tpu_operator.controllers.risk import read_node_risk  # noqa: F401

        cm = client.get_or_none("v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, ns)
        raw = ((cm or {}).get("data") or {}).get(consts.RISK_STATE_KEY, "")
        try:
            migrations = (json.loads(raw) or {}).get("migrations", [])
        except ValueError:
            migrations = []
    return {
        "predictive": predictive,
        "seed": seed,
        "phase": block.get("phase"),
        "passes": passes,
        "lost_steps": len(executed) - len(set(executed)),
        "continuity_ok": report["ok"],
        "failed_seen": "Failed" in phases_seen,
        "premigrated": bool(block.get("riskHandled")),
        "victim": victim,
        "kill_pass": next(
            (r[0] for r in schedule.log if r[1] == "inject"), None
        ),
        "pod_generations": len(trainers),
        "migrations": migrations,
    }


def _predict_false_alarm_run(seed: int = 20260807) -> dict:
    """The governance leg: a seeded precursor window with NO kill
    behind it (``false_alarm_at``). The scorer may migrate the gang at
    most ONCE (the budget's nextAttemptAt gate), must settle the
    prediction ``realized=false`` once the risk subsides past the grace
    window, release the host's budget — and the job must never see a
    Failed transition."""
    import tempfile

    from tpu_operator import consts
    from tpu_operator.api.tpujob import new_tpu_job
    from tpu_operator.controllers.job_controller import JobReconciler
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.controllers.risk import RiskScorer
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.sim import GangFaultSchedule, PodKubelet, make_torus_nodes

    ns = "tpu-operator"
    client = FakeClient()
    for node in make_torus_nodes((2, 2, 2), prefix="bench-fa"):
        node["metadata"]["labels"]["tpu.google.com/tpu.present"] = "true"
        client.create(node)
    store_dir = tempfile.mkdtemp(prefix="bench-falarm-")
    client.create(new_tpu_job("fa-job", {
        "workload": {"steps": 400},
        "gang": {"shape": "2x2x1", "minShape": "1x1x1"},
        "checkpoint": {"everySteps": 10, "dir": store_dir},
        "backoff": {"baseSeconds": 0.01, "maxSeconds": 0.05, "retryLimit": 10},
    }))
    job_rec = JobReconciler(client, ns)
    place_rec = PlacementReconciler(client, ns)
    kubelet = PodKubelet(client, ns)
    schedule = GangFaultSchedule(
        client, ns, "fa-job-slice", seed=seed + 1, classes=(),
        precursor_passes=6, false_alarm_at=[6],
    )
    risk = RiskScorer(client, ns)
    clock = [0.0]
    risk._now = lambda: clock[0]
    phases_seen = set()
    for _ in range(30):
        job_rec.reconcile(Request(name="fa-job"))
        place_rec.reconcile(QUEUE_REQUEST)
        kubelet.step()
        schedule.step()
        clock[0] += 30.0
        risk.sync()
        job = client.get("tpu.google.com/v1alpha1", "TPUJob", "fa-job")
        block = (job.get("status") or {}).get("job") or {}
        phases_seen.add(block.get("phase"))
    kubelet.stop()
    cm = client.get_or_none("v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, ns)
    raw = ((cm or {}).get("data") or {}).get(consts.RISK_STATE_KEY, "")
    try:
        state = json.loads(raw) or {}
    except ValueError:
        state = {}
    migrations = state.get("migrations", [])
    false_positives = [
        m for m in migrations if m.get("settled") and m.get("realized") is False
    ]
    budget_entries = {
        h: e for h, e in (state.get("hosts") or {}).items()
        if e.get("attempts") or e.get("nextAttemptAt")
    }
    return {
        "migrations": len(migrations),
        "false_positives": len(false_positives),
        "settled": all(m.get("settled") for m in migrations),
        "budget_released": not budget_entries,
        "failed_seen": "Failed" in phases_seen,
    }


def _predict_serving_drain() -> dict:
    """The serving half: a risky host under one replica takes the PR 14
    drain-then-re-place path — the replica re-seats AWAY from the risky
    host (the engine's risk-aware scorer) and the serving keeps at
    least one ready replica through the whole window."""
    from tpu_operator import consts
    from tpu_operator.api.tpuserving import new_tpu_serving
    from tpu_operator.controllers.placement_controller import (
        QUEUE_REQUEST,
        PlacementReconciler,
    )
    from tpu_operator.controllers.risk import RiskScorer
    from tpu_operator.controllers.serving_controller import ServingReconciler
    from tpu_operator.kube.controller import Request
    from tpu_operator.kube.fake import FakeClient
    from tpu_operator.kube.objects import new_object
    from tpu_operator.kube.sim import make_torus_nodes

    ns = "tpu-operator"
    client = FakeClient()
    for node in make_torus_nodes((4, 2, 1), prefix="bench-rs"):
        node["metadata"]["labels"]["tpu.google.com/tpu.present"] = "true"
        client.create(node)
    client.create(new_tpu_serving("risk-svc", {
        "model": {"shape": "2x1x1"},
        "replicas": {"min": 2, "max": 2, "targetRps": 10.0,
                     "cooldownSeconds": 0.0},
        "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 5},
    }))
    rec = ServingReconciler(client, ns)
    place = PlacementReconciler(client, ns)
    req = Request(name="risk-svc")
    risk = RiskScorer(client, ns)
    clock = [0.0]
    risk._now = lambda: clock[0]

    def block() -> dict:
        obj = client.get("tpu.google.com/v1alpha1", "TPUServing", "risk-svc")
        return (obj.get("status") or {}).get("serving") or {}

    for _ in range(8):
        rec.reconcile(req)
        place.reconcile(QUEUE_REQUEST)
        if block().get("ready") == 2:
            break
    placed_before = dict(block())
    replicas = sorted(placed_before.get("replicas") or {})
    target = replicas[0] if replicas else ""
    members = []
    if target:
        obj = client.get("tpu.google.com/v1alpha1", "TPUSlice", target)
        members = ((obj.get("status") or {}).get("placement") or {}).get("nodes") or []
    risky_host = members[0] if members else ""
    if risky_host:
        # a straggler artifact naming the replica's host: the risk
        # scorer's job, not the schedule's — serving gangs have no
        # trainer loop, so the precursor is seeded directly
        artifact = json.dumps({
            "hosts": len(members), "gang_step_p50_s": 1.0,
            "straggler_ratio": 2.0, "slowest_host": risky_host,
        })
        try:
            client.create(new_object("v1", "ConfigMap", f"{target}-gang", ns))
        except Exception:  # noqa: BLE001 — exists already
            pass
        client.patch(
            "v1", "ConfigMap", f"{target}-gang",
            {"metadata": {
                "labels": {"app.kubernetes.io/managed-by": "tpu-slice-manager"},
                "annotations": {consts.GANG_TELEMETRY_ANNOTATION: artifact},
            }}, ns,
        )
    min_ready = 2
    drained = False
    for _ in range(12):
        clock[0] += 30.0
        risk.sync()
        rec.reconcile(req)
        place.reconcile(QUEUE_REQUEST)
        ready = int(block().get("ready") or 0)
        min_ready = min(min_ready, ready)
        cm = client.get_or_none("v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, ns)
        raw = ((cm or {}).get("data") or {}).get(consts.RISK_STATE_KEY, "")
        try:
            drained = drained or bool((json.loads(raw) or {}).get("migrations"))
        except ValueError:
            pass
        if drained and ready == 2:
            break
    after = dict(block())
    final_nodes = []
    if target:
        obj = client.get("tpu.google.com/v1alpha1", "TPUSlice", target)
        final_nodes = ((obj.get("status") or {}).get("placement") or {}).get("nodes") or []
    return {
        "risky_host": risky_host,
        "drained": drained,
        "ready_before": placed_before.get("ready"),
        "ready_after": after.get("ready"),
        "min_ready_during_drain": min_ready,
        "replica_nodes_after": final_nodes,
        "re_placed_off_risky_host": bool(final_nodes) and risky_host not in final_nodes,
    }


def bench_predict(seed: int = 20260807) -> dict:
    """Predictive health (ISSUE 19): the planned-vs-unplanned pair on
    the SAME seeded host-death schedule (the measurable win), the
    false-alarm governance leg, and the serving drain leg."""
    planned = _predict_training_run(True, seed)
    unplanned = _predict_training_run(False, seed)
    false_alarm = _predict_false_alarm_run(seed)
    serving = _predict_serving_drain()
    return {
        "seed": seed,
        "planned": planned,
        "unplanned": unplanned,
        "false_alarm": false_alarm,
        "serving": serving,
        "planned_lost_steps": planned["lost_steps"],
        "unplanned_lost_steps": unplanned["lost_steps"],
        "false_positive_migrations": false_alarm["false_positives"],
    }


def predict_smoke() -> int:
    """CI gate (scripts/ci.sh): predictive health end to end —

    1. on the same seeded schedule (same pre-chosen victim, same kill
       pass) the predictive run walks the job off the dying host behind
       the checkpoint barrier for ZERO lost steps, while the reactive
       run rewinds to the last cadence checkpoint (>= 1 lost);
    2. the prediction is booked realized=true in the state CM;
    3. a seeded false alarm triggers at most ONE budget-gated migration,
       settles realized=false, releases the budget, and never drives the
       job through Failed;
    4. a risky serving host drains via the PR 14 path without the
       serving ever dropping below one ready replica, and the replica
       re-seats off the risky host.

    ci.sh runs the gate twice — plain and TPUOP_RACECHECK=1."""
    result = bench_predict()
    planned, unplanned = result["planned"], result["unplanned"]
    fa, serving = result["false_alarm"], result["serving"]
    checks = {
        "planned_succeeded": planned["phase"] == "Succeeded",
        "planned_zero_lost_steps": planned["lost_steps"] == 0,
        "planned_continuity_ok": planned["continuity_ok"],
        "job_premigrated": planned["premigrated"],
        "planned_never_failed": not planned["failed_seen"],
        "prediction_realized": any(
            m.get("settled") and m.get("realized") is True
            for m in planned["migrations"]
        ),
        "same_schedule": (
            bool(planned["victim"])
            and planned["victim"] == unplanned["victim"]
            and planned["kill_pass"] == unplanned["kill_pass"]
        ),
        "unplanned_succeeded": unplanned["phase"] == "Succeeded",
        "unplanned_lost_steps": unplanned["lost_steps"] >= 1,
        "false_alarm_at_most_one_migration": fa["migrations"] <= 1,
        "false_alarm_settled_unrealized": fa["migrations"] == fa["false_positives"],
        "false_alarm_budget_released": fa["budget_released"],
        "false_alarm_never_failed": not fa["failed_seen"],
        "serving_drained": serving["drained"],
        "serving_never_unroutable": serving["min_ready_during_drain"] >= 1,
        "serving_re_placed_off_risky_host": serving["re_placed_off_risky_host"],
    }
    violations = []
    if os.environ.get("TPUOP_RACECHECK") == "1":
        from tpu_operator.kube import racecheck

        violations = [repr(v) for v in racecheck.violations()]
    checks["racecheck_clean"] = not violations
    ok = all(checks.values())
    print(json.dumps({
        "metric": "predict_smoke",
        "ok": ok,
        "checks": checks,
        "planned_lost_steps": result["planned_lost_steps"],
        "unplanned_lost_steps": result["unplanned_lost_steps"],
        "false_positive_migrations": result["false_positive_migrations"],
        "victim": planned["victim"],
        "kill_pass": planned["kill_pass"],
        "serving": serving,
        "racecheck_violations": violations,
    }, separators=(",", ":")))
    return 0 if ok else 1


def placement_smoke() -> int:
    """CI gate (scripts/ci.sh): a full place/evict/re-place churn on the
    simulated 512-host torus must finish inside the budget with zero
    double-booked hosts — the regression shapes a broken allocator
    produces (overlap) or an accidentally super-linear search (blown
    budget)."""
    budget_s = 120.0
    result = bench_placement()
    ok = result["overlap_violations"] == 0 and result["elapsed_s"] <= budget_s
    print(json.dumps({
        "metric": "placement_smoke",
        "ok": ok,
        "budget_s": budget_s,
        **result,
    }, separators=(",", ":")))
    return 0 if ok else 1


def main() -> None:
    if "--scale-smoke" in sys.argv[1:]:
        raise SystemExit(scale_smoke())
    if "--chaos-smoke" in sys.argv[1:]:
        raise SystemExit(chaos_smoke())
    if "--placement-smoke" in sys.argv[1:]:
        raise SystemExit(placement_smoke())
    if "--trace-smoke" in sys.argv[1:]:
        raise SystemExit(trace_smoke())
    if "--telemetry-smoke" in sys.argv[1:]:
        raise SystemExit(telemetry_smoke())
    if "--fabric-smoke" in sys.argv[1:]:
        raise SystemExit(fabric_smoke())
    if "--autotune-smoke" in sys.argv[1:]:
        raise SystemExit(autotune_smoke())
    if "--job-smoke" in sys.argv[1:]:
        raise SystemExit(job_smoke())
    if "--serving-smoke" in sys.argv[1:]:
        raise SystemExit(serving_smoke())
    if "--pod-smoke" in sys.argv[1:]:
        raise SystemExit(pod_smoke())
    if "--defrag-smoke" in sys.argv[1:]:
        raise SystemExit(defrag_smoke())
    if "--compile-smoke" in sys.argv[1:]:
        raise SystemExit(compile_smoke())
    if "--predict-smoke" in sys.argv[1:]:
        raise SystemExit(predict_smoke())
    if "--tenant-smoke" in sys.argv[1:]:
        raise SystemExit(tenant_smoke())
    runs = [bench_install_to_ready() for _ in range(3)]
    value = statistics.median(runs)
    http_runs = [bench_install_to_ready(transport="http") for _ in range(3)]
    http_value = statistics.median(http_runs)
    scale_64 = bench_install_to_ready(nodes=64)  # 16 slices of v5e-16
    # apiserver traffic at scale over the wire, cached (informer-served
    # reads, the controller-runtime model) vs uncached (round-3's direct
    # reads): the requests-per-reconcile drop is what keeps a real
    # apiserver alive on large clusters. 3 s of steady state after Ready
    # so the rate reflects level-triggered reconciles, not just install.
    scale_http = {}
    # trace-driven attribution (ISSUE 6): the cached 64/256/1024 runs
    # also aggregate every reconcile trace into a per-controller
    # breakdown of wall time and request count by span kind — the
    # decomposition that explains the requests_per_reconcile curve
    from tpu_operator.kube import trace as trace_mod

    attribution = {}
    for label, nodes, cached in (
        ("64node_cached", 64, True),
        ("64node_direct", 64, False),
        ("256node_cached", 256, True),
        ("256node_direct", 256, False),
        # two orders of magnitude above the 64-node point; cached only
        # (the direct path's point is made at 64/256 — repeating it at
        # 1024+ would just burn minutes re-measuring a known O(nodes) cost)
        ("1024node_cached", 1024, True),
        ("4096node_cached", 4096, True),
        # the sharded control plane's design point (pods off above 1024:
        # kubelet bookkeeping, not control-plane cost)
        ("16384node_cached", 16384, True),
    ):
        attr = None
        if cached and nodes in (1024, 16384):
            # attribution at the two gate scales: 1024 (the queue-wait
            # baseline the sharded run is compared against) and 16384
            # (the sharded run itself, with per-shard owners)
            attr = TraceAttribution()
            trace_mod.reset_recorder().add_listener(attr)
        try:
            elapsed, stats = bench_install_to_ready(
                nodes=nodes, transport="http", cached_reads=cached,
                collect_stats=True,
                deadline_s=max(300.0, nodes * 0.06),
                settle_s=3.0,
                sim_pods=nodes <= 1024,
            )
            scale_http[label] = {"install_to_ready_s": round(elapsed, 3), **stats}
            if attr is not None:
                attribution[str(nodes)] = {
                    "traces": attr.traces,
                    "incomplete_traces": attr.incomplete,
                    "controllers": attr.block(),
                }
        except RuntimeError as e:
            scale_http[label] = {"error": str(e)}
    # install→Ready under the standard fault schedule (30 s outage, 5%
    # 5xx, 429 bursts, watch drops) — the robustness twin of the clean
    # number: how much failure costs, not just how fast success is
    try:
        chaos_s, chaos_director = bench_chaos_converge()
        chaos_block = {
            "chaos_converge_s": round(chaos_s, 3),
            "seed": chaos_director.seed,
            "faults_injected": len(chaos_director.fault_log),
            "fault_classes": sorted(chaos_director.fired_classes()),
        }
    except Exception as e:  # noqa: BLE001 — a chaos failure must not
        # crash the whole nightly bench; record it as the chaos result
        chaos_block = {"error": f"{type(e).__name__}: {e}"}
    # topology-aware placement over the churned 512-host torus:
    # time-to-place + end-state fragmentation (gated by --placement-smoke)
    try:
        placement_block = bench_placement()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        placement_block = {"error": f"{type(e).__name__}: {e}"}
    details = tpu_details()
    details["multiprocess_distributed"] = _multiprocess_distributed_details()
    # data-plane step-time telemetry: burn-in under the recorder +
    # the live gang's merged artifact (gated by --telemetry-smoke)
    telemetry = telemetry_block()
    # ICI fabric sweep: per-edge transfer timing + per-axis allreduce
    # latency on the virtual mesh (gated by --fabric-smoke)
    fabric = fabric_block()
    # kernel-autotune sweep: flash block grid + matmul tilings with the
    # default config measured in-grid (gated by --autotune-smoke)
    autotune = autotune_block()
    # elastic training through the gang fault schedule: resume latency,
    # lost-steps-per-fault, shrink step-time ratio (gated by --job-smoke)
    try:
        training = bench_training()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        training = {"error": f"{type(e).__name__}: {e}"}
    # traffic-driven serving: continuous-vs-static decode bench + the
    # diurnal autoscale drill (gated by --serving-smoke)
    try:
        serving = bench_serving()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        serving = {"error": f"{type(e).__name__}: {e}"}
    # the pod data plane: KV-affinity routing over worker pods + the
    # disaggregated prefill/decode pools (gated by --pod-smoke)
    try:
        pods = bench_pods()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        pods = {"error": f"{type(e).__name__}: {e}"}
    # capacity planning: best-fit vs defrag-aware at 4096 sim hosts +
    # the analytical model's calibrate-then-predict validation (gated
    # by --defrag-smoke)
    try:
        fleet_sim = bench_fleet_sim()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        fleet_sim = {"error": f"{type(e).__name__}: {e}"}
    # fleet compile cache: warm-vs-cold warm-start on the local backend
    # (gated by --compile-smoke)
    try:
        compile_cache = compile_block()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        compile_cache = {"error": f"{type(e).__name__}: {e}"}
    # predictive health: planned-vs-unplanned lost steps on the same
    # seeded precursor schedule + false-alarm governance (gated by
    # --predict-smoke)
    try:
        predict = bench_predict()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        predict = {"error": f"{type(e).__name__}: {e}"}
    # multi-tenant fairness: starvation vs fair-share on the seeded
    # two-tenant schedule + the 3:1 weight-tracking drill (gated by
    # --tenant-smoke)
    try:
        tenancy = bench_tenancy()
    except Exception as e:  # noqa: BLE001 — same isolation as chaos
        tenancy = {"error": f"{type(e).__name__}: {e}"}
    out = {
        "metric": "clusterpolicy_install_to_ready",
        "value": round(value, 3),
        "unit": "s",
        "vs_baseline": round(REFERENCE_READY_BOUND_S / value, 1),
        # the baseline is the reference's CI bound on real hardware; this
        # run isolates operator overhead on a sim apiserver with a 0.25 s
        # container start, so the ratio is an overhead isolate, not a
        # hardware-for-hardware comparison
        "vs_baseline_kind": "operator_overhead_isolate",
        "runs": [round(r, 3) for r in runs],
        # same flow with the apiserver served over real TCP and the
        # operator on the HTTP client: adds JSON serialization, watch
        # streams, and per-request connection setup to the measurement
        "http_transport_s": round(http_value, 3),
        "http_transport_runs": [round(r, 3) for r in http_runs],
        "baseline_s": REFERENCE_READY_BOUND_S,
        "sim_container_start_s": SIM_CONTAINER_START_S,
        "scale_64node_s": round(scale_64, 3),
        "scale_256node_s": scale_http.get("256node_cached", {}).get("install_to_ready_s"),
        "scale_1024node_s": scale_http.get("1024node_cached", {}).get("install_to_ready_s"),
        "scale_4096node_s": scale_http.get("4096node_cached", {}).get("install_to_ready_s"),
        "scale_16384node_s": scale_http.get("16384node_cached", {}).get("install_to_ready_s"),
        "scale_http_transport": scale_http,
        "attribution": attribution,
        "chaos_converge_s": chaos_block.get("chaos_converge_s"),
        "chaos": chaos_block,
        "placement": placement_block,
        "telemetry": telemetry,
        "fabric": fabric,
        "autotune": autotune,
        "training": training,
        "serving": serving,
        "pods": pods,
        "fleet_sim": fleet_sim,
        "compile": compile_cache,
        "predict": predict,
        "tenancy": tenancy,
        "details": details,
    }
    detail_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json")
    with open(detail_path, "w") as f:
        json.dump(out, f, indent=1)
    if "--full" in sys.argv[1:]:
        print(json.dumps(out))
        return
    line = json.dumps(_compact_summary(out), separators=(",", ":"))
    if len(line) >= 1800:
        # never fail (or truncate mid-object) after a multi-minute run:
        # drop to the bare driver contract and flag the overflow
        print(f"summary line too long ({len(line)} chars); printing core fields", file=sys.stderr)
        core = {k: out[k] for k in ("metric", "value", "unit", "vs_baseline")}
        line = json.dumps(core, separators=(",", ":"))
    print(line)


if __name__ == "__main__":
    main()
