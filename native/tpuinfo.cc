// tpuinfo: native TPU device probe.
//
// The TPU-native analog of the reference validator shelling out to
// lspci/nvidia-smi for device inventory (validator/metrics.go:250-299,
// validator/main.go:617-635): enumerate the accelerator device nodes the
// kernel exposes on a TPU VM and report them as JSON over a C ABI, so the
// Python agents (tfd_agent, validator) get a ground-truth chip count that
// does not depend on a working JAX/libtpu runtime.
//
// Device sources probed:
//   /dev/accel*              TPU v4+ VMs (Google "accel" devices)
//   /dev/vfio/*              passthrough topologies
//   /sys/class/accel/accel*  sysfs accel class (newer kernels)

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <string>
#include <vector>

namespace {

bool starts_with(const char* s, const char* prefix) {
  return std::strncmp(s, prefix, std::strlen(prefix)) == 0;
}

// TPUINFO_SCAN_ROOT prefixes every probed path (default ""): containers
// that mount the host filesystem somewhere other than / (e.g. /host) and
// tests that simulate a device inventory in a scratch directory both
// point the probe at their root.
std::string scan_root() {
  const char* env = std::getenv("TPUINFO_SCAN_ROOT");
  if (env == nullptr || env[0] == '\0') return "";
  std::string root(env);
  while (!root.empty() && root.back() == '/') root.pop_back();
  return root;
}

std::vector<std::string> list_dir(const char* path, const char* prefix) {
  std::vector<std::string> out;
  DIR* dir = ::opendir(path);
  if (dir == nullptr) return out;
  while (dirent* entry = ::readdir(dir)) {
    if (starts_with(entry->d_name, prefix) &&
        std::strcmp(entry->d_name, ".") != 0 &&
        std::strcmp(entry->d_name, "..") != 0) {
      out.push_back(std::string(path) + "/" + entry->d_name);
    }
  }
  ::closedir(dir);
  return out;
}

}  // namespace

extern "C" {

// Writes a JSON report into buf (NUL-terminated). Returns the number of
// bytes written (excluding NUL), or -1 if the buffer is too small.
int tpuinfo_probe(char* buf, int len) {
  const std::string root = scan_root();
  std::vector<std::string> devices = list_dir((root + "/dev").c_str(), "accel");
  std::vector<std::string> sys_devices =
      list_dir((root + "/sys/class/accel").c_str(), "accel");
  std::vector<std::string> vfio = list_dir((root + "/dev/vfio").c_str(), "");
  // /dev/accel and sysfs describe the same chips; take the larger view.
  int chip_count = static_cast<int>(
      devices.size() > sys_devices.size() ? devices.size() : sys_devices.size());

  std::string json = "{\"chip_count\":" + std::to_string(chip_count) + ",\"devices\":[";
  for (size_t i = 0; i < devices.size(); ++i) {
    if (i) json += ",";
    json += "\"" + devices[i] + "\"";
  }
  json += "],\"vfio_groups\":" +
          std::to_string(vfio.empty() ? 0 : vfio.size() - 1) +  // minus /dev/vfio/vfio
          "}";
  if (static_cast<int>(json.size()) + 1 > len) return -1;
  std::memcpy(buf, json.c_str(), json.size() + 1);
  return static_cast<int>(json.size());
}

// Per-chip (x,y,z) coordinates within this host's block of the torus.
//
// Source of truth is the libtpu/GKE host-bounds contract: the runtime
// publishes TPU_CHIPS_PER_HOST_BOUNDS="x,y,z" on TPU VMs (2,2,1 on
// v4/v5p hosts, 2,4,1 on single-host v5e-8). Without the env var, bounds
// fall back by enumerated chip count. Chip index walks x fastest, then
// y, then z — the same linearization libtpu uses for local devices.
// Consumed by the device plugin's GetPreferredAllocation so gang
// neighborhoods follow real torus adjacency instead of index windows.
//
// Writes {"bounds":[x,y,z],"coords":[[x,y,z],...]} JSON. Returns bytes
// written, or -1 if the buffer is too small.
int tpuinfo_chip_coords(int chip_count, char* buf, int len) {
  int bx = 0, by = 0, bz = 0;
  const char* env = std::getenv("TPU_CHIPS_PER_HOST_BOUNDS");
  if (env != nullptr) {
    char trailing = 0;
    // strict x,y,z — trailing tokens invalidate the value (keeps parity
    // with the Python fallback parser)
    if (std::sscanf(env, "%d,%d,%d%c", &bx, &by, &bz, &trailing) != 3) {
      bx = by = bz = 0;
    }
  }
  // sanity cap: host blocks are a handful of chips; a bogus env value
  // must not overflow bx*by*bz or build megabytes of JSON
  if (bx <= 0 || by <= 0 || bz <= 0 || bx > 64 || by > 64 || bz > 64 ||
      bx * by * bz > 4096) {
    bx = by = bz = 0;
  }
  if (bx <= 0 || by <= 0 || bz <= 0) {
    if (chip_count <= 0) {
      const std::string root = scan_root();
      std::vector<std::string> devices = list_dir((root + "/dev").c_str(), "accel");
      std::vector<std::string> sys_devices =
          list_dir((root + "/sys/class/accel").c_str(), "accel");
      chip_count = static_cast<int>(
          devices.size() > sys_devices.size() ? devices.size() : sys_devices.size());
    }
    switch (chip_count) {
      case 8: bx = 2; by = 4; bz = 1; break;
      case 4: bx = 2; by = 2; bz = 1; break;
      case 2: bx = 2; by = 1; bz = 1; break;
      default: bx = chip_count > 0 ? chip_count : 1; by = 1; bz = 1; break;
    }
  }
  std::string json = "{\"bounds\":[" + std::to_string(bx) + "," + std::to_string(by) +
                     "," + std::to_string(bz) + "],\"coords\":[";
  int n = bx * by * bz;
  for (int i = 0; i < n; ++i) {
    if (i) json += ",";
    json += "[" + std::to_string(i % bx) + "," + std::to_string((i / bx) % by) + "," +
            std::to_string(i / (bx * by)) + "]";
  }
  json += "]}";
  if (static_cast<int>(json.size()) + 1 > len) return -1;
  std::memcpy(buf, json.c_str(), json.size() + 1);
  return static_cast<int>(json.size());
}

// FNV-1a 64-bit content hash — shared with the Python side
// (tpu_operator/utils.py) so native consumers hash identically.
unsigned long long tpuinfo_fnv64(const char* data, unsigned long long len) {
  unsigned long long h = 0xCBF29CE484222325ULL;
  for (unsigned long long i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 0x100000001B3ULL;
  }
  return h;
}

}  // extern "C"
