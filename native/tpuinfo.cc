// tpuinfo: native TPU device probe.
//
// The TPU-native analog of the reference validator shelling out to
// lspci/nvidia-smi for device inventory (validator/metrics.go:250-299,
// validator/main.go:617-635): enumerate the accelerator device nodes the
// kernel exposes on a TPU VM and report them as JSON over a C ABI, so the
// Python agents (tfd_agent, validator) get a ground-truth chip count that
// does not depend on a working JAX/libtpu runtime.
//
// Device sources probed:
//   /dev/accel*              TPU v4+ VMs (Google "accel" devices)
//   /dev/vfio/*              passthrough topologies
//   /sys/class/accel/accel*  sysfs accel class (newer kernels)

#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <string>
#include <vector>

namespace {

bool starts_with(const char* s, const char* prefix) {
  return std::strncmp(s, prefix, std::strlen(prefix)) == 0;
}

std::vector<std::string> list_dir(const char* path, const char* prefix) {
  std::vector<std::string> out;
  DIR* dir = ::opendir(path);
  if (dir == nullptr) return out;
  while (dirent* entry = ::readdir(dir)) {
    if (starts_with(entry->d_name, prefix) &&
        std::strcmp(entry->d_name, ".") != 0 &&
        std::strcmp(entry->d_name, "..") != 0) {
      out.push_back(std::string(path) + "/" + entry->d_name);
    }
  }
  ::closedir(dir);
  return out;
}

}  // namespace

extern "C" {

// Writes a JSON report into buf (NUL-terminated). Returns the number of
// bytes written (excluding NUL), or -1 if the buffer is too small.
int tpuinfo_probe(char* buf, int len) {
  std::vector<std::string> devices = list_dir("/dev", "accel");
  std::vector<std::string> sys_devices = list_dir("/sys/class/accel", "accel");
  std::vector<std::string> vfio = list_dir("/dev/vfio", "");
  // /dev/accel and sysfs describe the same chips; take the larger view.
  int chip_count = static_cast<int>(
      devices.size() > sys_devices.size() ? devices.size() : sys_devices.size());

  std::string json = "{\"chip_count\":" + std::to_string(chip_count) + ",\"devices\":[";
  for (size_t i = 0; i < devices.size(); ++i) {
    if (i) json += ",";
    json += "\"" + devices[i] + "\"";
  }
  json += "],\"vfio_groups\":" +
          std::to_string(vfio.empty() ? 0 : vfio.size() - 1) +  // minus /dev/vfio/vfio
          "}";
  if (static_cast<int>(json.size()) + 1 > len) return -1;
  std::memcpy(buf, json.c_str(), json.size() + 1);
  return static_cast<int>(json.size());
}

// FNV-1a 64-bit content hash — shared with the Python side
// (tpu_operator/utils.py) so native consumers hash identically.
unsigned long long tpuinfo_fnv64(const char* data, unsigned long long len) {
  unsigned long long h = 0xCBF29CE484222325ULL;
  for (unsigned long long i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 0x100000001B3ULL;
  }
  return h;
}

}  // extern "C"
