#!/usr/bin/env python3
"""Image-entrypoint smoke: prove every docker/ image command actually boots.

No docker daemon exists in CI, so instead of building the images this
gate (a) parses each ``docker/Dockerfile*`` and resolves every entrypoint
wrapper to its ``python -m`` module, checking the ENTRYPOINT references a
defined wrapper and every COPY source exists; (b) imports each module;
and (c) STARTS each entrypoint as a real subprocess the way its
DaemonSet/Deployment would — standard in-cluster env pointed at a
TLS-served fake apiserver (``kube/httpserver.py``), a stub kubelet
registration socket, and sandboxed host paths — asserting an observable
startup effect per entrypoint (labels published, gang objects created,
kubelet registration, /metrics served, health probe up, status file
written, libtpu installed).

Reference counterpart: the e2e install proving the built images run
(tests/e2e/gpu_operator_test.go:104-170, validator/Dockerfile:55-57).
"""

from __future__ import annotations

import json
import os
import re
import shlex
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NS = "tpu-operator"
START_TIMEOUT = 90.0  # sitecustomize pre-imports jax: child startup is slow


def parse_dockerfiles() -> dict:
    """{wrapper_name: module} across docker/Dockerfile*; validates
    ENTRYPOINTs and COPY sources."""
    wrappers = {}
    for df in sorted(os.listdir(os.path.join(REPO, "docker"))):
        path = os.path.join(REPO, "docker", df)
        with open(path) as f:
            text = f.read()
        found = {}
        for mod, name in re.findall(
            r"exec python -m ([\w.]+) \"\$@\"\\n' > /usr/local/bin/([\w-]+)", text
        ):
            if name in found:
                raise SystemExit(f"{df}: wrapper {name!r} defined twice")
            found[name] = mod
        if not found:
            raise SystemExit(f"{df}: no entrypoint wrappers found")
        for m in re.finditer(r'^ENTRYPOINT \["([\w-]+)"\]', text, re.M):
            if m.group(1) not in found:
                raise SystemExit(f"{df}: ENTRYPOINT {m.group(1)!r} has no wrapper")
        for m in re.finditer(r"^COPY (?:--from=\w+ )?(\S+) ", text, re.M):
            src = m.group(1)
            if src.startswith("/"):
                continue  # build-stage path
            if not os.path.exists(os.path.join(REPO, src)):
                raise SystemExit(f"{df}: COPY source {src!r} missing from repo")
        wrappers.update(found)
    return wrappers


def import_check(modules) -> None:
    import importlib

    for mod in sorted(set(modules)):
        importlib.import_module(mod)
    print(f"ok: {len(set(modules))} entrypoint modules import")


class Harness:
    """TLS fake apiserver + seeded store + sandboxed host paths."""

    def __init__(self):
        from tpu_operator import consts
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.httpserver import FakeApiServer
        from tpu_operator.kube.sim import make_tpu_node

        self.tmp = tempfile.mkdtemp(prefix="image-smoke-")
        self.store = FakeClient()
        for i in range(2):  # 2-host pool: exercises the gang path
            node = make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "2x4", nodepool="pool-a")
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            self.store.create(node)
        self.apiserver = FakeApiServer(self.store, tls=True).start()
        # the in-cluster contract: SA dir with ca.crt (+ token, namespace)
        self.sa_dir = os.path.join(self.tmp, "serviceaccount")
        os.makedirs(self.sa_dir)
        with open(os.path.join(self.sa_dir, "ca.crt"), "wb") as f:
            f.write(self.apiserver.ca_pem)
        with open(os.path.join(self.sa_dir, "token"), "w") as f:
            f.write("smoke-token")
        with open(os.path.join(self.sa_dir, "namespace"), "w") as f:
            f.write(NS)
        self.install_dir = os.path.join(self.tmp, "libtpu")
        self.validation_dir = os.path.join(self.tmp, "validations")
        self.kubelet_dir = os.path.join(self.tmp, "kubelet")
        for d in (self.install_dir, self.validation_dir, self.kubelet_dir):
            os.makedirs(d)

    def env(self, **extra) -> dict:
        port = self.apiserver.httpd.server_address[1]
        env = dict(os.environ)
        env.update(
            {
                "KUBERNETES_SERVICE_HOST": "localhost",
                "KUBERNETES_SERVICE_PORT": str(port),
                "KUBE_SERVICEACCOUNT_DIR": self.sa_dir,
                "OPERATOR_NAMESPACE": NS,
                "NODE_NAME": "tpu-0",
                "VALIDATION_DIR": self.validation_dir,
                "LIBTPU_INSTALL_DIR": self.install_dir,
                "KUBELET_SOCKET_DIR": self.kubelet_dir,
                # keep children off the TPU relay: CPU platform, no axon
                "PALLAS_AXON_POOL_IPS": "",
                "JAX_PLATFORMS": "cpu",
            }
        )
        env.update(extra)
        return env

    def stop(self):
        self.apiserver.stop()


def spawn(module: str, args, env) -> subprocess.Popen:
    proc = subprocess.Popen(
        [sys.executable, "-m", module, *args],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # drain the pipe continuously: a chatty child would otherwise block on
    # a full pipe buffer before reaching its observable startup effect,
    # and the smoke would misreport a startup timeout
    proc.out_lines = []

    def _drain():
        for line in proc.stdout:
            proc.out_lines.append(line)

    import threading

    threading.Thread(target=_drain, daemon=True).start()
    return proc


def wait_for(desc: str, predicate, proc=None, timeout: float = START_TIMEOUT):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        if proc is not None and proc.poll() is not None:
            raise SystemExit(
                f"FAIL {desc}: process exited rc={proc.returncode}\n"
                f"{''.join(proc.out_lines)[-3000:]}"
            )
        time.sleep(0.25)
    out = ""
    if proc is not None:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()  # SIGTERM ignored: force exit so the drain sees EOF
            proc.wait(timeout=10)
        out = "".join(proc.out_lines)[-3000:]
    raise SystemExit(f"FAIL {desc}: condition not met in {timeout}s\n{out}")


def finish(proc: subprocess.Popen) -> None:
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def http_ok(url: str) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=2) as resp:
            return resp.status == 200
    except Exception:  # noqa: BLE001 — still starting
        return False


def free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def smoke_entrypoints(wrappers: dict, harness: Harness) -> None:
    from tpu_operator import consts

    checks_run = []

    def check(name):
        if name not in wrappers:
            raise SystemExit(f"FAIL: expected wrapper {name!r} in docker/ images")
        checks_run.append(name)
        return wrappers[name]

    # tpuop-cfg: CRD generation to stdout, exits 0
    proc = subprocess.run(
        [sys.executable, "-m", check("tpuop-cfg"), "generate", "crds"],
        env=harness.env(),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=START_TIMEOUT,
    )
    if proc.returncode != 0 or "CustomResourceDefinition" not in proc.stdout:
        raise SystemExit(f"FAIL tpuop-cfg: rc={proc.returncode}\n{proc.stderr[-2000:]}")
    print("ok: tpuop-cfg generate crds")

    # tpuop-lint: static analysis over the shipped artifacts, exits 0
    # (a seeded defect failing the build is covered by tests/test_lint.py;
    # here the check is that the in-image entrypoint boots and runs clean)
    proc = subprocess.run(
        [sys.executable, "-m", check("tpuop-lint"), "--format", "json"],
        env=harness.env(),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=START_TIMEOUT * 4,  # renders every state + walks the AST
    )
    if proc.returncode != 0 or '"summary"' not in proc.stdout:
        raise SystemExit(f"FAIL tpuop-lint: rc={proc.returncode}\n{proc.stderr[-2000:]}")
    print("ok: tpuop-lint --format json")

    # libtpu-installer: oneshot install of a fake .so into the sandbox
    fake_so = os.path.join(harness.tmp, "libtpu-src.so")
    with open(fake_so, "wb") as f:
        f.write(b"\x7fELF fake libtpu payload")
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            check("libtpu-installer"),
            "--oneshot",
            "--source",
            fake_so,
            "--version",
            "9.9.9-smoke",
            "--install-dir",
            harness.install_dir,
        ],
        env=harness.env(),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=START_TIMEOUT,
    )
    lib = os.path.join(harness.install_dir, "libtpu.so")
    if proc.returncode != 0 or not os.path.exists(lib):
        raise SystemExit(f"FAIL libtpu-installer: rc={proc.returncode}\n{proc.stderr[-2000:]}")
    print("ok: libtpu-installer --oneshot installed", os.readlink(lib))

    # tpu-validator COMPONENT=libtpu: consumes the install above, writes
    # the status-file barrier, exits 0
    proc = subprocess.run(
        [sys.executable, "-m", check("tpu-validator")],
        env=harness.env(COMPONENT="libtpu"),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=START_TIMEOUT,
    )
    status = os.path.join(harness.validation_dir, consts.LIBTPU_READY_FILE)
    if proc.returncode != 0 or not os.path.exists(status):
        raise SystemExit(f"FAIL tpu-validator: rc={proc.returncode}\n{proc.stdout[-2000:]}")
    print("ok: tpu-validator COMPONENT=libtpu wrote", consts.LIBTPU_READY_FILE)

    # tpu-feature-discovery: publishes TFD labels onto its Node via the
    # TLS apiserver
    proc = spawn(check("tpu-feature-discovery"), [], harness.env())
    wait_for(
        "tpu-feature-discovery labels",
        lambda: consts.TFD_TOPOLOGY_LABEL
        in (harness.store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}),
        proc,
    )
    finish(proc)
    print("ok: tpu-feature-discovery published node labels over TLS")

    # tpu-node-discovery: the NFD-analog bootstrap — a bare (non-GKE) node
    # plus a simulated /dev/accel* inventory must come out labelled
    from tpu_operator.kube.sim import make_bare_node

    harness.store.create(make_bare_node("bare-0"))
    scan_root = os.path.join(harness.tmp, "scanroot")
    os.makedirs(os.path.join(scan_root, "dev"))
    for i in range(4):
        open(os.path.join(scan_root, "dev", f"accel{i}"), "w").close()
    proc = spawn(
        check("tpu-node-discovery"),
        [],
        harness.env(
            NODE_NAME="bare-0",
            TPUINFO_SCAN_ROOT=scan_root,
            TPU_ACCELERATOR_TYPE="v5litepod-4",
            TPU_TOPOLOGY="",  # override anything the axon runtime injected
        ),
    )
    wait_for(
        "tpu-node-discovery labels",
        lambda: (harness.store.get("v1", "Node", "bare-0")["metadata"].get("labels") or {}).get(
            consts.TFD_ACCELERATOR_TYPE_LABEL
        )
        == "tpu-v5-lite-podslice",
        proc,
    )
    finish(proc)
    print("ok: tpu-node-discovery labelled a bare node from the device probe")

    # tpu-slice-manager: renders gang Service/ConfigMap for the 2-host pool
    proc = spawn(check("tpu-slice-manager"), [], harness.env())
    wait_for(
        "tpu-slice-manager gang configmap",
        lambda: any(
            cm["metadata"]["name"].endswith("-gang")
            for cm in harness.store.list("v1", "ConfigMap", NS)
        ),
        proc,
    )
    finish(proc)
    print("ok: tpu-slice-manager created gang objects")

    # tpu-device-plugin: registers with the stub kubelet over the unix socket
    from tpu_operator.kube.sim import StubKubelet

    kubelet = StubKubelet(os.path.join(harness.kubelet_dir, "kubelet.sock"))
    try:
        proc = spawn(check("tpu-device-plugin"), [], harness.env())
        wait_for("tpu-device-plugin registration", kubelet.event.is_set, proc)
        finish(proc)
        req = kubelet.requests[0]
        if req.resource_name != consts.TPU_RESOURCE_NAME:
            raise SystemExit(f"FAIL tpu-device-plugin: registered {req.resource_name!r}")
    finally:
        kubelet.stop()
    print("ok: tpu-device-plugin registered", consts.TPU_RESOURCE_NAME, "with stub kubelet")

    # tpu-health-monitor: probes the sandboxed host surfaces and publishes
    # the node health label + per-chip annotation over the TLS apiserver.
    # The sandbox is made healthy deterministically: 4 fake /dev/accel*
    # nodes matching the node's 4 allocatable chips, the libtpu ready
    # marker from the installer check above, and a stub plugin socket.
    health_scan = os.path.join(harness.tmp, "health-scanroot")
    os.makedirs(os.path.join(health_scan, "dev"))
    for i in range(4):
        open(os.path.join(health_scan, "dev", f"accel{i}"), "w").close()
    # own socket-dir sandbox: the real plugin check above may have left a
    # socket inode in harness.kubelet_dir that open() cannot truncate
    health_kubelet = os.path.join(harness.tmp, "health-kubelet")
    os.makedirs(health_kubelet)
    open(os.path.join(health_kubelet, "tpu-device-plugin.sock"), "w").close()
    health_dir = os.path.join(harness.tmp, "health")
    proc = spawn(
        check("tpu-health-monitor"),
        [],
        harness.env(
            TPUINFO_SCAN_ROOT=health_scan,
            KUBELET_SOCKET_DIR=health_kubelet,
            HEALTH_DIR=health_dir,
            HEALTH_CHECK_INTERVAL="1",
            TPU_HEALTH_ACTIVE_PROBES="off",
        ),
    )
    wait_for(
        "tpu-health-monitor verdict",
        lambda: (harness.store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}).get(
            consts.TPU_HEALTH_LABEL
        )
        == consts.HEALTH_HEALTHY,
        proc,
    )
    finish(proc)
    with open(os.path.join(health_dir, consts.HEALTH_VERDICTS_FILE)) as f:
        verdicts = json.load(f)
    if verdicts.get("verdict") != consts.HEALTH_HEALTHY or len(verdicts.get("chips", {})) != 4:
        raise SystemExit(f"FAIL tpu-health-monitor: bad verdicts file {verdicts}")
    print("ok: tpu-health-monitor published node health over TLS + verdicts file")

    # tpu-autotuner: oneshot pass over TLS — elected node with a valid
    # cached entry reads as a cache hit (node get + results-ConfigMap
    # get in-cluster, zero writes; the real sweep is bench's job)
    import json as _json

    node = harness.store.get("v1", "Node", "tpu-0")
    node["metadata"]["labels"][consts.AUTOTUNE_ELECTED_LABEL] = consts.AUTOTUNE_ELECTED
    harness.store.update(node)
    entry = {
        "generation": "v5e",
        "libtpu_version": "smoke",
        "platform": "tpu",
        "results": {
            fam: {"s256_h1_d64": {"winner": {"block_q": 128, "block_k": 128, "rate": 1.0},
                                  "configs": []}}
            for fam in ("flash_fwd", "flash_fwd_bwd", "matmul", "int8")
        },
    }
    from tpu_operator.kube.objects import new_object

    harness.store.create(new_object(
        "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS,
        data={"v5e.json": _json.dumps(entry)},
    ))
    proc = subprocess.run(
        [sys.executable, "-m", check("tpu-autotuner"), "--oneshot"],
        env=harness.env(LIBTPU_VERSION="smoke"),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=START_TIMEOUT,
    )
    if proc.returncode != 0 or '"cache-hit"' not in proc.stdout:
        raise SystemExit(
            f"FAIL tpu-autotuner: rc={proc.returncode}\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
        )
    print("ok: tpu-autotuner --oneshot read the sweep cache over TLS (cache hit)")

    # tpu-compile-cache: oneshot pass over TLS — elected node whose
    # requested executable already has a valid cached record reads as a
    # cache hit (node get + cache-ConfigMap get in-cluster, zero
    # writes; the real prewarm compile is bench's job)
    node = harness.store.get("v1", "Node", "tpu-0")
    node["metadata"]["labels"][consts.COMPILE_CACHE_ELECTED_LABEL] = (
        consts.COMPILE_CACHE_ELECTED
    )
    harness.store.update(node)
    cache_entry = {
        "generation": "v5e",
        "libtpu_version": "smoke",
        "records": {"2x4/smokehash": {"seconds": 1.0, "source": "prewarm"}},
    }
    prewarm_requests = {
        "requests": {
            "v5e/2x4/smokehash": {
                "generation": "v5e", "topology": "2x4", "model": "smokehash",
            }
        }
    }
    harness.store.create(new_object(
        "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, NS,
        data={
            "v5e.json": _json.dumps(cache_entry),
            consts.COMPILE_PREWARM_REQUEST_KEY: _json.dumps(prewarm_requests),
        },
    ))
    proc = subprocess.run(
        [sys.executable, "-m", check("tpu-compile-cache"), "--oneshot"],
        env=harness.env(LIBTPU_VERSION="smoke"),
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=START_TIMEOUT,
    )
    if proc.returncode != 0 or '"cache-hit"' not in proc.stdout:
        raise SystemExit(
            f"FAIL tpu-compile-cache: rc={proc.returncode}\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
        )
    print("ok: tpu-compile-cache --oneshot read the compile cache over TLS (cache hit)")

    # tpu-metrics-exporter: serves prometheus metrics
    port = free_port()
    proc = spawn(check("tpu-metrics-exporter"), ["--port", str(port)], harness.env())
    wait_for(
        "tpu-metrics-exporter /metrics",
        lambda: http_ok(f"http://127.0.0.1:{port}/metrics"),
        proc,
    )
    finish(proc)
    print("ok: tpu-metrics-exporter served /metrics")

    # tpu-operator: the controller-manager boots in-cluster (TLS apiserver),
    # health + metrics endpoints answer
    health, metrics = free_port(), free_port()
    proc = spawn(
        check("tpu-operator"),
        [
            "--health-probe-bind-address",
            f"127.0.0.1:{health}",
            "--metrics-bind-address",
            f"127.0.0.1:{metrics}",
        ],
        harness.env(),
    )
    wait_for("tpu-operator healthz", lambda: http_ok(f"http://127.0.0.1:{health}/healthz"), proc)
    wait_for("tpu-operator metrics", lambda: http_ok(f"http://127.0.0.1:{metrics}/metrics"), proc)
    finish(proc)
    print("ok: tpu-operator controller-manager booted against the TLS apiserver")

    missed = set(wrappers) - set(checks_run)
    if missed:
        raise SystemExit(f"FAIL: wrappers with no startup check: {sorted(missed)}")


def main() -> None:
    wrappers = parse_dockerfiles()
    print(f"entrypoints: {json.dumps(wrappers, indent=1)}")
    import_check(wrappers.values())
    import importlib.util

    if importlib.util.find_spec("cryptography") is None:
        # the live-boot harness is a TLS fake apiserver and the in-cluster
        # client only speaks https — without x509 material there is nothing
        # real to boot against. Imports above still gate the entrypoints.
        print(
            "IMAGE SMOKE: PASS (imports only — cryptography unavailable, "
            "TLS live-boot harness skipped)"
        )
        return
    harness = Harness()
    try:
        smoke_entrypoints(wrappers, harness)
    finally:
        harness.stop()
    print(f"IMAGE SMOKE: PASS ({len(wrappers)} entrypoints)")


if __name__ == "__main__":
    main()
