#!/usr/bin/env bash
# CI gate (reference: the Makefile's unit-test + gpuop-cfg validate +
# golden-asset drift targets in one pass).
set -euo pipefail
cd "$(dirname "$0")/.."
echo "== unit tests =="
python3 -m pytest tests/ -q
echo "== golden render drift =="
python3 -m pytest tests/test_render_states.py -q -k golden
echo "== rendered chart lints clean =="
python3 -m tpu_operator.cmd.tpuop_cfg render --values deploy/values.yaml > /tmp/ci-render.yaml
python3 - <<'PY'
import yaml
from tpu_operator.cmd.tpuop_cfg import validate_clusterpolicy
docs = list(yaml.safe_load_all(open("/tmp/ci-render.yaml")))
cps = [d for d in docs if d and d.get("kind") == "ClusterPolicy"]
problems = [p for cp in cps for p in validate_clusterpolicy(cp)]
assert cps and not problems, problems
print(f"OK ({len(docs)} objects, {len(cps)} ClusterPolicy)")
PY
echo "== e2e =="
bash tests/scripts/end-to-end.sh
echo "CI: PASS"
