#!/usr/bin/env bash
# CI gate (reference: the Makefile's unit-test + gpuop-cfg validate +
# golden-asset drift targets in one pass).
set -euo pipefail
cd "$(dirname "$0")/.."
echo "== unit tests (includes golden render drift) =="
python3 -m pytest tests/ -q
echo "== rendered chart lints clean =="
python3 scripts/validate_rendered.py
echo "== e2e =="
bash tests/scripts/end-to-end.sh
echo "CI: PASS"
