#!/usr/bin/env bash
# CI gate (reference: the Makefile's unit-test + gpuop-cfg validate +
# golden-asset drift targets in one pass).
set -euo pipefail
cd "$(dirname "$0")/.."
echo "== unit tests (includes golden render drift) =="
# the explicit image-smoke step below covers tests/test_image_smoke.py;
# skip the in-suite copy so CI boots each entrypoint once, not twice.
# slow-marked drills (the full-length 256-node/30s-outage chaos soak)
# stay out of the gate — the bounded chaos smoke below covers the path
TPU_OPERATOR_SKIP_IMAGE_SMOKE_TEST=1 python3 -m pytest tests/ -q -m "not slow"
echo "== rendered chart lints clean =="
python3 scripts/validate_rendered.py
echo "== tpuop-lint static analysis (error severity fails the build) =="
# all six families: manifest, rbac, drift, metrics, concurrency, and the
# reconcile-contract rules (TPUOP-K: ownership-checked deletes, shared-CM
# key ownership, fail-closed reads, publish-once status, gated charges).
# JSON to a file for artifact upload AND a human-readable echo on failure
if ! python3 -m tpu_operator.cmd.tpuop_lint --format json > /tmp/lint-report.json; then
  python3 -m tpu_operator.cmd.tpuop_lint --format text || true
  echo "tpuop-lint FAILED (see /tmp/lint-report.json)"
  exit 1
fi
python3 - <<'EOF'
import json
summary = json.load(open("/tmp/lint-report.json"))["summary"]
print(f"tpuop-lint: {summary}")
EOF
echo "== racecheck: multi-thread drills + compressed chaos soak under instrumented locks =="
# runtime concurrency gate: the leader-failover and crash-recovery
# drills plus the bounded chaos soak re-run with TPUOP_RACECHECK=1 —
# every lock is instrumented (per-thread acquisition order into one
# global graph) and the informer-cache/FakeClient-store mutation
# tripwires are armed; any lock-order cycle or concurrent-writer hit
# fails the owning test via the conftest guard
TPUOP_RACECHECK=1 python3 -m pytest tests/test_racecheck.py -q
TPUOP_RACECHECK=1 python3 -m pytest tests/test_chaos.py \
  -q -m "not slow" -k "Soak or CrashRestart or LeaderFailover"
echo "== bench smoke: requests-per-reconcile + write rate stay flat 1024 -> 16384 nodes =="
# O(changes) gate for the sharded control plane: fails when
# rpr[16384] > 1.5 x rpr[1024], or when steady writes-per-flip stops
# being flat — the regression shapes a reintroduced full-scan,
# full-object write, or broken shard routing produce
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --scale-smoke
echo "== bench smoke (racecheck leg): sharded scale path under instrumented locks =="
# the same gate re-run with every lock instrumented (TPUOP_RACECHECK=1)
# at a compressed scale pair — instrumented acquires cost ~an order of
# magnitude, so the leg is bounded the same way the chaos soak's
# racecheck leg is; any lock-order cycle or mutation-tripwire hit fails
# the gate via the bench's own racecheck.violations() check
TPUOP_RACECHECK=1 TPUOP_SCALE_SMOKE_NODES="256,1024" \
  JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --scale-smoke
echo "== placement smoke: place/evict/re-place churn on a 512-host torus =="
# topology gate: the full churn cycle must finish inside the budget with
# ZERO double-booked hosts — the regression shapes a broken allocator
# (overlap) or an accidentally super-linear block search (blown budget)
# produce
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --placement-smoke
echo "== trace smoke: every reconcile yields a complete trace; recorder stays bounded =="
# flight-recorder gate: install -> Ready through the chaos schedule with
# full tracing (no orphan spans, >=95% of each reconcile's wall time
# accounted, retries visible as attempt children), the ring buffer
# provably wraps, and the 4096-node sim keeps the recorder under its
# measured memory cap
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --trace-smoke
echo "== telemetry smoke: grey failure detected, remediated, gang re-placed =="
# data-plane gate: a gang member's matmul probe 30% below the generation
# floor must flip tpu_exporter_perf_degraded, read as a straggler in the
# gang artifact, drive the health FSM cordon->revalidate, re-place the
# gang off the slow host, and leave every new series on the endpoints
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --telemetry-smoke
echo "== fabric smoke: degraded edge blamed on the link, gang re-places around it =="
# edge-aware blame gate: a seeded single-edge degradation must be
# attributed to the LINK (recorded in the link-health map, both endpoint
# hosts stay schedulable, the gang re-places around the cut) and a
# multi-edge-one-endpoint degradation to the HOST (perf label -> FSM);
# the tpu_operator_ici_link_* series must live and die with their pool
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --fabric-smoke
echo "== autotune smoke: one sweep per generation, floors tighten, cache hits are write-free =="
# closed-loop autotune gate: seeded two-generation sim — exactly one
# sweep per generation fleet-wide, results + winners land in the
# ConfigMaps, the folded v5e floor matches perf.py's measured roof x
# FLOOR_FRACTION, the exporter hot-reloads it, a second pass and a
# late-joining node are zero-write cache hits, and the real local
# flash sweep proves the tuned config >= the hardcoded default
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --autotune-smoke
echo "== job smoke: checkpoint -> shrink -> resume -> grow with epoch continuity =="
# elastic-training gate: a TPUJob through the seeded gang fault schedule
# (host death, grey failure, link cut, preemption) must end Succeeded
# with contiguous epoch history (no step lost beyond the last
# checkpoint), shrinking only to allocator-ranked blocks and growing
# back on every heal; an unplaceable-min-shape job must quarantine in
# Failed with an Event instead of crash-looping the placement queue
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --job-smoke
echo "== serving smoke: burst -> scale-up -> route -> fragmentation-aware scale-down =="
# traffic-driven serving gate: the continuous-batching decode engine
# must beat the static-batch baseline >= 1.5x tokens/s/chip on the same
# kernels; the seeded diurnal sim must scale up through placement with
# p99 TTFT inside the SLO, exclude a fabric-degraded replica from
# routing (zero requests), scale down via the fragmentation-aware
# victim, and retire every serving series when the CR is deleted
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --serving-smoke
echo "== pod smoke: KV-affinity routing over worker pods + disaggregated pools =="
# pod data-plane gate: worker pods under the sim kubelet with the
# KV-aware router — warm multi-turn sessions must beat cold single-shot
# TTFT at equal load on the seeded diurnal arrivals (session affinity +
# delta-prefill), the disaggregated prefill/decode pools must each
# scale on their OWN signal (prefill TTFT p99 vs SLO; decode tokens/s
# floor) with paged-KV handoffs flowing between them, and deleting the
# CRs must sweep every worker pod
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --pod-smoke
echo "== pod smoke (racecheck leg): the same gate under instrumented locks =="
TPUOP_RACECHECK=1 JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --pod-smoke
echo "== defrag smoke: fragmented torus -> migration -> the 4x4x4 lands =="
# capacity-planning gate: on the seeded fragmented 512-host torus the
# defrag controller must land a previously-unplaceable 4x4x4 gang with
# fragmentation strictly decreasing (serving replicas drain-then-
# re-place; a TPUJob gang moves behind the checkpoint barrier with its
# step watermark intact), propose ZERO migrations while any placement
# is queued, and the fleet simulator's defrag-aware policy must beat
# best-fit on p99 time-to-place under the seeded churn schedule
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --defrag-smoke
echo "== defrag smoke (racecheck leg): the same gate under instrumented locks =="
TPUOP_RACECHECK=1 JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --defrag-smoke
echo "== compile smoke: warm scale-ups via the fleet compile cache =="
# compile-cache gate: the first replica of a (generation, topology,
# model) key pays the measured cold XLA compile and publishes it; the
# second resolves the record and starts FAR warmer; the AOT prewarm
# handshake (serving request -> election -> agent compile -> ack) closes
# with zero steady-state writes; a simulated libtpu bump invalidates
# exactly the stale entries and re-compiles once per generation with
# demand; the what-if warm ETA prices strictly below the cold one
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --compile-smoke
echo "== compile smoke (racecheck leg): the same gate under instrumented locks =="
TPUOP_RACECHECK=1 JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --compile-smoke
echo "== predict smoke: risk-scored host walked off before it dies =="
# predictive-health gate: on the SAME seeded host-death schedule (same
# pre-chosen victim, same kill pass) the risk scorer's planned
# checkpoint-barrier migration must lose ZERO steps while the reactive
# run rewinds to the last cadence checkpoint; a seeded false alarm may
# trigger at most ONE budget-gated migration, settles realized=false
# and releases the budget; a risky serving host drains without the
# serving ever dropping below one ready replica
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --predict-smoke
echo "== predict smoke (racecheck leg): the same gate under instrumented locks =="
TPUOP_RACECHECK=1 JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --predict-smoke
echo "== tenant smoke: fair-share bounds the small team the stock order starves =="
# multi-tenant fairness gate: on the seeded two-tenant contention
# schedule (512 sim hosts) the stock priority-then-FIFO order starves
# the small team (p99 time-to-place at least doubles the fair run's,
# or gangs never place); equal guaranteed TPUQuotas bound the small
# team's p99 and place every gang, at no fleet-utilization cost vs the
# untagged single-tenant baseline; zero TPUQuota stays byte-identical
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --tenant-smoke
echo "== tenant smoke (racecheck leg): the same gate under instrumented locks =="
TPUOP_RACECHECK=1 JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --tenant-smoke
echo "== chaos smoke: install -> Ready through the seeded fault schedule =="
# bounded chaos-soak gate: converge through 5xx/429/410/resets, periodic
# watch drops, and a full-outage window; fails if any configured fault
# class never fired (a vacuous schedule) or convergence never happens
JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 python3 bench.py --chaos-smoke
echo "== image entrypoints boot (no docker daemon: resolved from Dockerfiles) =="
python3 scripts/image_smoke.py
echo "== e2e =="
bash tests/scripts/end-to-end.sh
echo "== real-helm render golden (optional: needs helm) =="
# 42 = no helm binary (skip); 43 = helm agreed with helmlite but the
# golden snapshot was only just bootstrapped (gate unarmed until the
# snapshot is committed) — both are non-failures, but 43 is surfaced
rc=0
bash tests/scripts/helm-golden.sh || rc=$?
if [ "$rc" -eq 43 ]; then
  echo "NOTE: helm golden bootstrapped, commit tests/golden/helm-template.yaml"
elif [ "$rc" -ne 0 ] && [ "$rc" -ne 42 ]; then
  echo "helm golden FAILED (rc=$rc)"
  exit "$rc"
fi
echo "== real-apiserver e2e (optional: needs docker + kind) =="
# 42 is kind-e2e.sh's skip sentinel, chosen outside pytest's 0-5 range
# so a crashed suite can never read as "kind not installed"
rc=0
bash tests/scripts/kind-e2e.sh || rc=$?
if [ "$rc" -ne 0 ] && [ "$rc" -ne 42 ]; then
  echo "kind e2e FAILED (rc=$rc)"
  exit "$rc"
fi
echo "CI: PASS"
