#!/usr/bin/env python3
"""Regenerate golden render files (reference: internal/state/testdata/golden)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import yaml

from tpu_operator.api import ClusterPolicy
from tpu_operator.api.clusterpolicy import new_cluster_policy
from tpu_operator.catalog import InfoCatalog
from tpu_operator.states import STATE_ORDER, new_cluster_policy_states

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", "golden")


def main():
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    cp = ClusterPolicy.from_unstructured(
        new_cluster_policy(spec={"metricsExporter": {"serviceMonitor": {"enabled": True}}})
    )
    catalog = InfoCatalog(cluster_policy=cp)
    for state in new_cluster_policy_states():
        objs = state.renderer.render_objects(state.get_render_data(catalog))
        path = os.path.join(GOLDEN_DIR, f"{state.name}.yaml")
        with open(path, "w") as f:
            yaml.safe_dump_all(objs, f, default_flow_style=False, sort_keys=False)
        print(f"wrote {path} ({len(objs)} objects)")
    assert set(STATE_ORDER) == {s.name for s in new_cluster_policy_states()}


if __name__ == "__main__":
    main()
