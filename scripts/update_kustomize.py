#!/usr/bin/env python3
"""Regenerate deploy/kustomize/ from the chart render path.

Reference: config/default/kustomization.yaml (+ crd/rbac/manager bases)
gives non-helm installs a kubectl-apply path. Here the bases are
GENERATED from the same renderer `tpuop-cfg render` uses, so the three
install paths (helm chart, tpuop-cfg render, kustomize) can never drift:
tests/test_kustomize.py re-renders and fails on any difference.

Layout (mirrors kubebuilder's config/ convention):
    deploy/kustomize/crd/       both CRDs
    deploy/kustomize/rbac/      ServiceAccount, ClusterRole(+Binding)
    deploy/kustomize/manager/   Namespace + operator Deployment
    deploy/kustomize/samples/   a default ClusterPolicy CR (not in
                                default/ — installing the CR is the
                                user's opt-in, like config/samples)
    deploy/kustomize/default/   aggregates crd + rbac + manager
"""

from __future__ import annotations

import os
import sys

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KUSTOMIZE_DIR = os.path.join(REPO, "deploy", "kustomize")

# kind -> (base dir, file name)
PLACEMENT = {
    "CustomResourceDefinition": ("crd", None),  # per-object file by name
    "ServiceAccount": ("rbac", "serviceaccount.yaml"),
    "ClusterRole": ("rbac", "clusterrole.yaml"),
    "ClusterRoleBinding": ("rbac", "clusterrolebinding.yaml"),
    "Namespace": ("manager", "namespace.yaml"),
    "Deployment": ("manager", "deployment.yaml"),
    "ClusterPolicy": ("samples", "clusterpolicy.yaml"),
    "Secret": ("manager", "webhook-secret.yaml"),
    "ValidatingWebhookConfiguration": ("manager", "webhook.yaml"),
}


def generate() -> dict:
    """Returns {relative path: yaml text} for every file to write."""
    from tpu_operator.chart import render_chart

    with open(os.path.join(REPO, "deploy", "values.yaml")) as f:
        values = yaml.safe_load(f)
    objs = render_chart(values)
    files: dict = {}
    resources: dict = {"crd": [], "rbac": [], "manager": [], "samples": []}
    for obj in objs:
        kind = obj["kind"]
        if kind not in PLACEMENT:
            raise SystemExit(f"no kustomize placement for rendered kind {kind!r}")
        base, fname = PLACEMENT[kind]
        if fname is None:
            fname = obj["metadata"]["name"].split(".")[0] + ".yaml"
        rel = os.path.join(base, fname)
        text = yaml.safe_dump(obj, sort_keys=False)
        if rel in files:
            files[rel] += "---\n" + text
        else:
            files[rel] = text
            resources[base].append(fname)
    for base, names in resources.items():
        if not names:
            continue
        files[os.path.join(base, "kustomization.yaml")] = yaml.safe_dump(
            {
                "apiVersion": "kustomize.config.k8s.io/v1beta1",
                "kind": "Kustomization",
                "resources": sorted(names),
            },
            sort_keys=False,
        )
    files[os.path.join("default", "kustomization.yaml")] = yaml.safe_dump(
        {
            "apiVersion": "kustomize.config.k8s.io/v1beta1",
            "kind": "Kustomization",
            # samples/ (the ClusterPolicy CR) is deliberately excluded:
            # creating the CR is the user's opt-in, mirroring
            # config/samples in the reference layout
            "resources": ["../crd", "../rbac", "../manager"],
        },
        sort_keys=False,
    )
    return files


def main() -> int:
    files = generate()
    for rel, text in sorted(files.items()):
        path = os.path.join(KUSTOMIZE_DIR, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
        print(f"wrote {path}")
    # prune stale files (an object removed from the chart must take its
    # base file with it, or the drift test fails unrecoverably by
    # regeneration alone) — but ONLY inside the generated bases: users
    # may keep hand-written overlays (deploy/kustomize/overlays/...)
    # next to them, and those are not ours to delete
    generated_bases = {rel.split(os.sep)[0] for rel in files}
    for base in sorted(generated_bases):
        base_dir = os.path.join(KUSTOMIZE_DIR, base)
        for root, _, names in os.walk(base_dir):
            for name in names:
                path = os.path.join(root, name)
                rel = os.path.relpath(path, KUSTOMIZE_DIR)
                if rel not in files:
                    os.unlink(path)
                    print(f"pruned {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
