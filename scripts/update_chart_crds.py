#!/usr/bin/env python3
"""Regenerate the Helm chart's crds/ from the API definitions (the chart
ships CRDs alongside templates like the reference's
deployments/gpu-operator/crds/). tests/test_helm_chart.py asserts drift."""

import os
import sys

import yaml

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tpu_operator.api.crds import all_crds  # noqa: E402

CRD_DIR = os.path.join(ROOT, "deploy", "helm", "tpu-operator", "crds")


def main() -> None:
    os.makedirs(CRD_DIR, exist_ok=True)
    expected = set()
    for crd in all_crds():
        name = crd["metadata"]["name"].split(".")[0] + ".yaml"
        expected.add(name)
        path = os.path.join(CRD_DIR, name)
        with open(path, "w") as f:
            yaml.safe_dump(crd, f, default_flow_style=False, sort_keys=False)
        print(f"wrote {path}")
    on_disk = {n for n in os.listdir(CRD_DIR) if n.endswith((".yaml", ".yml"))}
    for stale in on_disk - expected:
        os.unlink(os.path.join(CRD_DIR, stale))
        print(f"removed stale {stale}")


if __name__ == "__main__":
    main()
