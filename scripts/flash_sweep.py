"""Sweep flash-attention block sizes on the real chip.

Times the 8k causal forward (and optionally fwd+bwd) for a grid of
(block_q, block_k) configs using the relay-safe two-point estimator and
prints one JSON line per config. Run on the axon TPU backend (default
platform); pass --fwd-bwd to add the training path for each config.
"""

from __future__ import annotations

import argparse
import json
import sys
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from tpu_operator.workloads.flashattention import flash_attention
from tpu_operator.workloads.timing import attention_grad_chain, two_point_min_timing


def time_config(seq_len, heads, head_dim, block_q, block_k, iters, reps,
                fwd_bwd=False):
    shape = (1, seq_len, heads, head_dim)
    keys = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(key, shape, dtype=jnp.bfloat16) for key in keys)
    fn = lambda a, kk, vv: flash_attention(
        a, kk, vv, causal=True, block_q=block_q, block_k=block_k
    )

    @partial(jax.jit, static_argnames="n")
    def chain(q, k, v, s, n):
        def step(i, acc):
            return fn(acc, k, v).astype(q.dtype)

        out = lax.fori_loop(0, n, step, q * s)
        return jnp.float32(out.sum())

    timing = two_point_min_timing(
        lambda s, n: float(chain(q, k, v, s, n)), iters, 4 * iters, reps
    )
    t = timing.per_iter_s or timing.inclusive_per_iter_s
    flops = 2 * 2 * heads * seq_len**2 * head_dim / 2
    out = {
        "seq_len": seq_len,
        "block_q": block_q,
        "block_k": block_k,
        "fwd_ms": round(t * 1e3, 3),
        "fwd_tflops": round(flops / t / 1e12, 1),
        "stable": timing.per_iter_s is not None,
    }
    if fwd_bwd:
        gchain = attention_grad_chain(fn, q, k, v)
        gt = two_point_min_timing(
            lambda s, n: float(gchain(q, k, v, s, n)), iters, 4 * iters, reps
        )
        ts = gt.per_iter_s or gt.inclusive_per_iter_s
        out["fwd_bwd_ms"] = round(ts * 1e3, 3)
        out["fwd_bwd_stable"] = gt.per_iter_s is not None
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--reps", type=int, default=4)
    ap.add_argument("--fwd-bwd", action="store_true")
    ap.add_argument(
        "--configs",
        default="256x1024,256x512,512x512,512x1024,128x1024,256x2048,512x2048,1024x1024",
        help="comma-separated BQxBK pairs",
    )
    args = ap.parse_args()
    print(json.dumps({"platform": jax.devices()[0].platform}), flush=True)
    for cfg in args.configs.split(","):
        bq, bk = (int(x) for x in cfg.split("x"))
        try:
            r = time_config(
                args.seq, args.heads, args.head_dim, bq, bk,
                args.iters, args.reps, fwd_bwd=args.fwd_bwd,
            )
        except Exception as e:  # keep sweeping past an invalid config
            r = {"block_q": bq, "block_k": bk, "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
