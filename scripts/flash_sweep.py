"""Sweep flash-attention block sizes on the real chip.

Thin CLI over ``tpu_operator.workloads.autotune`` — the one sweep
implementation the ``state-autotuner`` operand also runs (this script
used to carry its own copy of the timing chain; now there is exactly
one). Times the causal forward (and optionally fwd+bwd) for a grid of
(block_q, block_k) configs using the relay-safe two-point estimator and
prints one JSON line per config, keeping the historical contract:
a ``{"platform": ...}`` header, then per-config lines with
``seq_len``/``block_q``/``block_k``/``fwd_ms``/``fwd_tflops``/
``stable`` (+ ``fwd_bwd_ms``/``fwd_bwd_stable`` under ``--fwd-bwd``;
``error`` records for invalid configs). ``--prune-ratio`` enables the
harness's dominated-config pruning (0 = measure everything, the
historical behavior).
"""

from __future__ import annotations

import argparse
import json

import jax

from tpu_operator.workloads.autotune import sweep_flash


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--reps", type=int, default=4)
    ap.add_argument("--fwd-bwd", action="store_true")
    ap.add_argument(
        "--prune-ratio", type=float, default=0.0,
        help="skip full timing of configs this factor slower than the "
        "probe best (0 = measure every config)",
    )
    ap.add_argument(
        "--configs",
        default="256x1024,256x512,512x512,512x1024,128x1024,256x2048,512x2048,1024x1024",
        help="comma-separated BQxBK pairs",
    )
    args = ap.parse_args()
    grid = []
    for cfg in args.configs.split(","):
        bq, bk = (int(x) for x in cfg.split("x"))
        grid.append((bq, bk))
    prune = args.prune_ratio if args.prune_ratio > 0 else float("inf")
    print(json.dumps({"platform": jax.devices()[0].platform}), flush=True)

    def run(fwd_bwd):
        records, _ = sweep_flash(
            seq_len=args.seq, heads=args.heads, head_dim=args.head_dim,
            configs=grid, iters=args.iters, reps=args.reps,
            fwd_bwd=fwd_bwd, prune_ratio=prune,
        )
        return {(r.config["block_q"], r.config["block_k"]): r for r in records}

    # configs the grid rejects up front (non-dividing blocks) still get
    # an error record, like the historical per-config try/except
    swept = run(fwd_bwd=False)
    bwd = run(fwd_bwd=True) if args.fwd_bwd else {}
    for bq, bk in grid:
        r = swept.get((bq, bk))
        if r is None:
            out = {"block_q": bq, "block_k": bk,
                   "error": f"ValueError: blocks do not divide seq {args.seq}"}
        elif r.error:
            out = {"block_q": bq, "block_k": bk, "error": r.error}
        else:
            out = {
                "seq_len": args.seq,
                "block_q": bq,
                "block_k": bk,
                "fwd_ms": round(r.time_ms, 3),
                "fwd_tflops": round(r.rate, 1),
                "stable": r.stable,
            }
            if r.pruned:
                out["pruned"] = True
            g = bwd.get((bq, bk))
            if g is not None and not g.error:
                out["fwd_bwd_ms"] = round(g.time_ms, 3)
                out["fwd_bwd_stable"] = g.stable
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
