#!/usr/bin/env python3
"""Render the chart and lint the ClusterPolicy it produces (the
gpuop-cfg-in-CI analog, Makefile `validate` target)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import yaml

from tpu_operator.chart import render_chart
from tpu_operator.cmd.tpuop_cfg import validate_clusterpolicy


def main() -> int:
    with open(os.path.join(os.path.dirname(__file__), "..", "deploy", "values.yaml")) as f:
        values = yaml.safe_load(f)
    objs = render_chart(values)
    cps = [o for o in objs if o.get("kind") == "ClusterPolicy"]
    problems = [p for cp in cps for p in validate_clusterpolicy(cp)]
    for p in problems:
        print(f"INVALID: {p}", file=sys.stderr)
    if not cps:
        print("no ClusterPolicy rendered", file=sys.stderr)
        return 1
    print(f"rendered chart OK: {len(objs)} objects, {len(cps)} ClusterPolicy")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
