{{/*
Common labels for operator-owned install objects (reference:
deployments/gpu-operator/templates/_helpers.tpl). Verified against
helmlite's define/include support — keep in sync with the
tpuop-cfg render path (deploy/templates/0500_deployment.yaml).
*/}}
{{- define "tpu-operator.labels" -}}
{{- /* user labels merge UNDER the chart's own (merge: leftmost wins),
      so extraLabels can never clobber the app selector labels; hasKey
      distinguishes an absent key from an explicitly empty map (both
      valid, neither may break merge) */ -}}
{{- $extra := ternary (.Values.operator.extraLabels | default (dict)) (dict) (hasKey .Values.operator "extraLabels") -}}
{{- toYaml (merge (dict
      "app" "tpu-operator"
      "app.kubernetes.io/name" "tpu-operator"
      "app.kubernetes.io/instance" .Release.Name) $extra) -}}
{{- end }}
