{{/*
Common labels for operator-owned install objects (reference:
deployments/gpu-operator/templates/_helpers.tpl). Verified against
helmlite's define/include support — keep in sync with the
tpuop-cfg render path (deploy/templates/0500_deployment.yaml).
*/}}
{{- define "tpu-operator.labels" -}}
app: tpu-operator
app.kubernetes.io/name: tpu-operator
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end }}
